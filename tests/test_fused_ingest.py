"""Fused device-resident ingest vs the host golden path — parity suite.

The fused backend (ops/ingest.fused_ingest_step + driver/ingest.FusedIngest)
replaces BatchScanDecoder -> ScanAssembler -> ScanFilterChain.process_raw
with ONE compiled program per frame batch.  This suite pins the contract
that makes it shippable: **bit-exact** node buffers and filter outputs
against the host path on identical wire streams, across

  * all six measurement wire formats,
  * corrupt / resync streams (checksum + CRC faults),
  * the revolution-overflow cap (head-keep truncation),
  * carry continuity across arbitrary chunk boundaries (prev-frame,
    sync-edge, smoothing carries as device scalars),
  * max_revs batch overflow (the assembler's newest-wins drop),
  * answer-type switches (decode state resets, filter window survives),
  * the node/FSM end-to-end seam (``ingest_backend=fused``).

Timestamps ride as f32 epoch offsets on the fused path (the host path is
f64), so ts0/duration are compared to tolerance, not bit-exactly; node
values and filter outputs ARE exact.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES
from rplidar_ros2_driver_tpu.driver.assembly import ScanAssembler
from rplidar_ros2_driver_tpu.driver.decode import BatchScanDecoder
from rplidar_ros2_driver_tpu.driver.ingest import FusedIngest
from rplidar_ros2_driver_tpu.filters.chain import (
    ScanFilterChain,
    resolve_ingest_backend,
)
from rplidar_ros2_driver_tpu.protocol import crc as crcmod
from rplidar_ros2_driver_tpu.protocol.constants import Ans
from rplidar_ros2_driver_tpu.protocol.timing import SAMPLES_PER_FRAME

from test_live_decode import _make_stream, _rng

ALL_FORMATS = sorted(SAMPLES_PER_FRAME, key=int)
# paired capsule formats: checksum faults isolate to adjacent pairs
CAPSULE_FORMATS = [
    Ans.MEASUREMENT_CAPSULED,
    Ans.MEASUREMENT_CAPSULED_ULTRA,
    Ans.MEASUREMENT_DENSE_CAPSULED,
    Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED,
]
# small chain geometry: the parity question is bit-exactness, not scale
BEAMS = 256
TS_TOL = 1e-4  # f32 epoch offsets vs the host's f64 (see module docstring)


def _params(**over):
    base = dict(
        filter_backend="cpu",
        filter_chain=("clip", "median", "voxel"),
        filter_window=4,
        voxel_grid_size=32,
    )
    base.update(over)
    return DriverParams(**base)


def _clamped_host_nodes(scan: dict) -> np.ndarray:
    """The wire clamps (ops/filters._pack_compact_rows) applied to a host
    assembler scan dict — what the filter step actually sees, and what the
    fused path applies pre-scatter (ops/ingest._wire_clamp)."""
    a = np.asarray(scan["angle_q14"]).astype(np.uint32) & 0xFFFF
    d = np.minimum(
        np.asarray(scan["dist_q2"], np.int64).astype(np.uint32),
        np.uint32(0x3FFFF),
    )
    q = np.asarray(scan["quality"]).astype(np.uint32) & 0xFF
    f = np.asarray(scan["flag"]).astype(np.uint32) & 0x3F
    return np.stack([a, d, q, f], axis=1).astype(np.int32)


def _feed_both(ans, frames, host_sinks, fused, chunk_rng, t0=100.0):
    """Feed identical (frame, rx_ts) batches to the host decoder(s) and the
    fused engine, in random chunk sizes (1..4 — the fused bucket)."""
    t = t0
    i = 0
    while i < len(frames):
        k = int(chunk_rng.integers(1, 5))
        batch = []
        for f in frames[i : i + k]:
            t += 0.002
            batch.append((f, t))
        for sink in host_sinks:
            sink.on_measurement_batch(int(ans), list(batch))
        fused.on_measurement_batch(int(ans), list(batch))
        i += k
    return t


def _run_host(ans, frames, chunk_seed=5, max_nodes=None, t0=100.0):
    """Host golden: decoder + assembler tap; returns completed scan dicts."""
    completed = []
    asm = ScanAssembler(
        max_nodes=max_nodes or MAX_SCAN_NODES,
        on_complete=lambda s: completed.append(dict(s)),
    )
    dec = BatchScanDecoder(asm)
    _feed_both(ans, frames, [dec], _NullSink(), np.random.default_rng(chunk_seed), t0)
    return completed


class _NullSink:
    def on_measurement_batch(self, ans_type, items):
        pass


def _run_pair(ans, frames, *, chunk_seed=5, max_revs=6, max_nodes=None,
              params=None, with_chain=True, t0=100.0):
    """Feed one stream through BOTH backends; returns
    (host scan dicts, host chain outputs, fused (out, ts0, dur) list, fused)."""
    params = params or _params()
    completed = []
    asm = ScanAssembler(
        max_nodes=max_nodes or MAX_SCAN_NODES,
        on_complete=lambda s: completed.append(dict(s)),
    )
    dec = BatchScanDecoder(asm)
    fused = FusedIngest(
        params, beams=BEAMS, capacity=max_nodes, max_revs=max_revs,
        emit_nodes=True, buckets=(4,),
    )
    _feed_both(ans, frames, [dec], fused, np.random.default_rng(chunk_seed), t0)
    fused_outs = fused.flush()
    host_outs = []
    if with_chain:
        chain = ScanFilterChain(params, beams=BEAMS, warmup=False)
        for s in completed:
            out = chain.process_raw(
                s["angle_q14"], s["dist_q2"], s["quality"], s["flag"]
            )
            host_outs.append((out, s["ts0"], s["duration"]))
    return completed, host_outs, fused_outs, fused


def _assert_outputs_equal(host_outs, fused_outs):
    assert len(host_outs) == len(fused_outs)
    for k, ((ho, hts0, hdur), (fo, fts0, fdur)) in enumerate(
        zip(host_outs, fused_outs)
    ):
        for field in ("ranges", "intensities", "points_xy", "point_mask", "voxel"):
            h = np.asarray(getattr(ho, field))
            f = np.asarray(getattr(fo, field))
            assert np.array_equal(h, f), f"rev {k}: {field} diverged"
        assert abs(hts0 - fts0) < TS_TOL, (k, hts0, fts0)
        assert abs(hdur - fdur) < TS_TOL, (k, hdur, fdur)


class TestFusedParity:
    """Bit-exact bytes -> filter-output parity on clean streams."""

    @pytest.mark.parametrize("ans", ALL_FORMATS)
    def test_all_formats_bit_exact(self, ans):
        frames = _make_stream(ans, 60, _rng(), syncs=(0, 15, 30, 45))
        completed, host_outs, fused_outs, fused = _run_pair(ans, frames)
        assert len(completed) >= 2, "stream closed no revolutions — bad fixture"
        assert fused.revs_dropped == 0 and fused.wires_dropped == 0
        _assert_outputs_equal(host_outs, fused_outs)
        assert fused.scans_completed == len(completed)

    @pytest.mark.parametrize("ans", ALL_FORMATS)
    def test_assembled_node_buffers_match(self, ans):
        """The segmented-scatter revolution buffers equal the assembler's
        (after the shared wire clamps), node for node."""
        frames = _make_stream(ans, 60, _rng(), syncs=(0, 15, 30, 45))
        params = _params()
        completed = []
        asm = ScanAssembler(on_complete=lambda s: completed.append(dict(s)))
        dec = BatchScanDecoder(asm)
        fused = FusedIngest(
            params, beams=BEAMS, max_revs=6, emit_nodes=True, buckets=(4,)
        )
        _feed_both(ans, frames, [dec], fused, np.random.default_rng(5))
        got = []
        while True:
            entry = fused._pop()
            if entry is None:
                break
            from rplidar_ros2_driver_tpu.ops.ingest import unpack_ingest_result

            res = unpack_ingest_result(entry[0], entry[1])
            for k in range(res.n_completed):
                got.append(
                    (res.nodes[k][: res.counts[k]],
                     res.node_ts[k][: res.counts[k]] + entry[2])
                )
        assert len(got) == len(completed)
        for k, (s, (nodes, node_ts)) in enumerate(zip(completed, got)):
            exp = _clamped_host_nodes(s)
            assert np.array_equal(exp, nodes), f"rev {k}: node values diverged"
            assert np.allclose(s["node_ts"], node_ts, atol=TS_TOL)


class TestCorruptAndResync:
    @pytest.mark.parametrize("ans", CAPSULE_FORMATS)
    def test_checksum_faults_stay_bit_exact(self, ans):
        frames = _make_stream(
            ans, 60, _rng(), syncs=(0,), corrupt=(7, 8, 23, 40)
        )
        completed, host_outs, fused_outs, _ = _run_pair(ans, frames)
        assert len(completed) >= 1
        _assert_outputs_equal(host_outs, fused_outs)

    def test_hq_crc_faults_stay_bit_exact(self):
        frames = list(
            _make_stream(Ans.MEASUREMENT_HQ, 40, _rng(), syncs=(0, 10, 20, 30))
        )
        for k in (5, 13):  # flip a payload byte: CRC32 fails on both paths
            fr = bytearray(frames[k])
            fr[40] ^= 0xFF
            frames[k] = bytes(fr)
        completed, host_outs, fused_outs, _ = _run_pair(
            Ans.MEASUREMENT_HQ, frames
        )
        assert len(completed) >= 2
        _assert_outputs_equal(host_outs, fused_outs)

    def test_ans_type_switch_resets_stream_keeps_window(self):
        """A scan-mode change resets decode/assembly state on both paths;
        the rolling filter window must survive on both."""
        a1, a2 = Ans.MEASUREMENT_DENSE_CAPSULED, Ans.MEASUREMENT_HQ
        f1 = _make_stream(a1, 40, _rng(), syncs=(0,))
        f2 = _make_stream(a2, 30, _rng(), syncs=(0, 8, 16, 24))
        params = _params()
        completed = []
        asm = ScanAssembler(on_complete=lambda s: completed.append(dict(s)))
        dec = BatchScanDecoder(asm)
        fused = FusedIngest(params, beams=BEAMS, max_revs=6, buckets=(4,))
        chain = ScanFilterChain(params, beams=BEAMS, warmup=False)
        rng = np.random.default_rng(11)
        t = _feed_both(a1, f1, [dec], fused, rng)
        # the host decoder resets the assembler on the type change itself
        _feed_both(a2, f2, [dec], fused, rng, t0=t + 1.0)
        fused_outs = fused.flush()
        host_outs = [
            (chain.process_raw(
                s["angle_q14"], s["dist_q2"], s["quality"], s["flag"]
            ), s["ts0"], s["duration"])
            for s in completed
        ]
        assert len(completed) >= 3  # revolutions from BOTH modes
        _assert_outputs_equal(host_outs, fused_outs)

    def test_reset_clears_partial_carries_window(self):
        """reset() (scan stop/start) drops the partial revolution and
        pending wires but carries the filter window — mirroring the host
        path, where _begin_streaming resets decoder+assembler while the
        node's chain object persists."""
        ans = Ans.MEASUREMENT_DENSE_CAPSULED
        frames = _make_stream(ans, 60, _rng(), syncs=(0,))
        params = _params()
        completed = []
        asm = ScanAssembler(on_complete=lambda s: completed.append(dict(s)))
        dec = BatchScanDecoder(asm)
        fused = FusedIngest(params, beams=BEAMS, max_revs=6, buckets=(4,))
        chain = ScanFilterChain(params, beams=BEAMS, warmup=False)
        rng = np.random.default_rng(3)
        t = _feed_both(ans, frames[:30], [dec], fused, rng)
        fused_outs = fused.flush()
        # stream restart on both paths
        fused.reset()
        dec.reset()
        asm.reset()
        _feed_both(ans, frames[30:], [dec], fused, rng, t0=t + 5.0)
        fused_outs += fused.flush()
        host_outs = [
            (chain.process_raw(
                s["angle_q14"], s["dist_q2"], s["quality"], s["flag"]
            ), s["ts0"], s["duration"])
            for s in completed
        ]
        assert len(completed) >= 2
        _assert_outputs_equal(host_outs, fused_outs)


class TestOverflowSemantics:
    def test_revolution_overflow_cap_head_keep(self):
        """An oversized revolution truncates head-keep at max_nodes on
        both paths (the assembler's 8192 cap, scaled down here)."""
        ans = Ans.MEASUREMENT_DENSE_CAPSULED
        frames = _make_stream(ans, 60, _rng(), syncs=(0,))
        cap = 64  # << nodes per revolution in this stream
        completed, host_outs, fused_outs, fused = _run_pair(
            ans, frames, max_nodes=cap
        )
        assert len(completed) >= 1
        for s in completed:
            assert len(s["angle_q14"]) <= cap
        _assert_outputs_equal(host_outs, fused_outs)

    def test_max_revs_batch_overflow_drops_oldest(self):
        """More completed revolutions in one dispatch than max_revs: the
        oldest drop (the assembler's newest-wins double buffer), counted
        in revs_dropped, and the survivor is the newest."""
        ans = Ans.MEASUREMENT  # 1 node/frame: syncs land densely in a batch
        frames = _make_stream(ans, 16, _rng(), syncs=tuple(range(0, 16, 2)))
        params = _params()
        fused = FusedIngest(params, beams=BEAMS, max_revs=1, buckets=(4,))
        # one 4-frame batch holds 2 syncs -> up to 2 completions per dispatch
        t = 50.0
        batches = []
        for i in range(0, len(frames), 4):
            batch = []
            for f in frames[i : i + 4]:
                t += 0.002
                batch.append((f, t))
            batches.append(batch)
        for b in batches:
            fused.on_measurement_batch(int(ans), b)
        outs = fused.flush()
        host_completed = _run_host(ans, frames, t0=50.0)
        assert fused.revs_dropped > 0
        assert len(outs) == len(host_completed) - fused.revs_dropped
        # survivors are the newest of each overflowing dispatch: every
        # fused ts0 must appear in the host series (no synthesized revs)
        host_ts0 = np.array([s["ts0"] for s in host_completed])
        for _, ts0, _ in outs:
            assert np.min(np.abs(host_ts0 - ts0)) < TS_TOL


class TestLongSessionTimestamps:
    def test_stamps_stay_exact_hours_into_a_session(self):
        """Per-dispatch re-basing keeps on-device f32 offsets bounded by
        one revolution's span: ts0/duration must hold TS_TOL with rx
        stamps 10 hours up the monotonic clock (a single session-epoch
        anchor drifts to ~4 ms f32 ulp there and fails this)."""
        ans = Ans.MEASUREMENT_DENSE_CAPSULED
        frames = _make_stream(ans, 60, _rng(), syncs=(0, 15, 30, 45))
        completed, host_outs, fused_outs, _ = _run_pair(
            ans, frames, t0=36_000.0
        )
        assert len(completed) >= 2
        _assert_outputs_equal(host_outs, fused_outs)


class TestSlotLoweringParity:
    @pytest.mark.parametrize("impl", ["cond", "fori"])
    def test_both_slot_lowerings_bit_exact_vs_host(self, impl):
        """The per-revolution slot section has two lowerings (cond-gated
        static unroll vs traced-trip fori_loop; picked per filter-state
        size on the live path, see ops/ingest._slot_impl_for) — BOTH must
        be bit-exact against the host golden path."""
        ans = Ans.MEASUREMENT_DENSE_CAPSULED
        frames = _make_stream(ans, 60, _rng(), syncs=(0, 15, 30, 45))
        params = _params()
        completed = []
        asm = ScanAssembler(on_complete=lambda s: completed.append(dict(s)))
        dec = BatchScanDecoder(asm)
        fused = FusedIngest(
            params, beams=BEAMS, max_revs=6, buckets=(4,), slot_impl=impl
        )
        _feed_both(int(ans), frames, [dec], fused, np.random.default_rng(5))
        fused_outs = fused.flush()
        chain = ScanFilterChain(params, beams=BEAMS, warmup=False)
        host_outs = [
            (chain.process_raw(
                s["angle_q14"], s["dist_q2"], s["quality"], s["flag"]
            ), s["ts0"], s["duration"])
            for s in completed
        ]
        assert len(completed) >= 2
        _assert_outputs_equal(host_outs, fused_outs)


class TestCarryContinuity:
    @pytest.mark.parametrize("ans", CAPSULE_FORMATS)
    def test_chunk_boundaries_do_not_matter(self, ans):
        """Two different random chunkings of one stream produce identical
        filter outputs: the prev-frame / sync-edge / smoothing carries are
        exact across every dispatch boundary."""
        frames = _make_stream(ans, 48, _rng(), syncs=(0,))
        params = _params()

        def run(seed):
            fused = FusedIngest(params, beams=BEAMS, max_revs=6, buckets=(4,))
            _feed_both(
                int(ans), frames, [], fused, np.random.default_rng(seed)
            )
            return fused.flush()

        a, b = run(1), run(2)
        assert len(a) == len(b) and len(a) >= 1
        for (oa, ta, da), (ob, tb, db) in zip(a, b):
            assert np.array_equal(np.asarray(oa.ranges), np.asarray(ob.ranges))
            assert np.array_equal(np.asarray(oa.voxel), np.asarray(ob.voxel))
            assert abs(ta - tb) < TS_TOL and abs(da - db) < TS_TOL


class TestSeamPlumbing:
    def test_resolve_ingest_backend(self):
        assert resolve_ingest_backend("auto") == "host"
        assert resolve_ingest_backend("host") == "host"
        assert resolve_ingest_backend("fused") == "fused"

    def test_params_validation(self):
        with pytest.raises(ValueError):
            DriverParams(ingest_backend="warp").validate()
        with pytest.raises(ValueError):
            DriverParams(ingest_backend="fused").validate()  # no filter_chain
        _params(ingest_backend="fused").validate()

    def test_meta_length_roundtrip(self):
        from rplidar_ros2_driver_tpu.filters.chain import config_from_params
        from rplidar_ros2_driver_tpu.ops.filters import wire_output_len
        from rplidar_ros2_driver_tpu.ops.ingest import (
            ingest_config_for,
            ingest_meta_len,
            unpack_ingest_result,
        )
        from rplidar_ros2_driver_tpu.protocol.timing import TimingDesc

        fcfg = config_from_params(_params(), BEAMS, platform="cpu")
        for ans in ALL_FORMATS:
            for emit in (False, True):
                icfg = ingest_config_for(
                    int(ans), TimingDesc(), fcfg, max_nodes=128, max_revs=2,
                    emit_nodes=emit,
                )
                zero = (
                    np.zeros(ingest_meta_len(icfg), np.float32),
                    np.zeros((2, wire_output_len(fcfg)), np.float32),
                    np.zeros((2, 128, 4), np.float32),
                    np.zeros((2, 128), np.float32),
                )
                res = unpack_ingest_result(zero, icfg)
                assert res.n_completed == 0 and res.outputs == []
                with pytest.raises(ValueError):
                    unpack_ingest_result(
                        (np.zeros(ingest_meta_len(icfg) + 1, np.float32),)
                        + zero[1:],
                        icfg,
                    )

    def test_single_frame_shim(self):
        ans = Ans.MEASUREMENT_HQ
        frames = _make_stream(ans, 12, _rng(), syncs=(0, 4, 8))
        fused = FusedIngest(_params(), beams=BEAMS, max_revs=6, buckets=(4,))
        for f in frames:
            fused.on_measurement(int(ans), f)
        outs = fused.flush()
        assert len(outs) == 2  # 3 syncs -> 2 closed revolutions


class TestFusedNodeE2E:
    def test_node_publishes_through_fused_seam(self):
        """ingest_backend=fused end to end: sim device wire frames ->
        RealLidarDriver pump -> FusedIngest -> FSM -> publisher."""
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import (
            SimConfig,
            SimulatedDevice,
        )
        from rplidar_ros2_driver_tpu.node.fsm import FsmTimings
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode, launch
        from rplidar_ros2_driver_tpu.node.publisher import CollectingPublisher

        sim = SimulatedDevice(
            SimConfig(points_per_rev=3200, frame_rate_hz=800.0)
        ).start()
        params = _params(
            channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
            ingest_backend="fused", scan_mode="DenseBoost",
        )
        pub = CollectingPublisher()
        node = RPlidarNode(
            params, pub,
            driver_factory=lambda: RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0,
            ),
            fsm_timings=FsmTimings.fast(),
        )
        try:
            launch(node)
            assert node.fused_ingest is not None
            assert node.chain is None  # the fused engine owns the window
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and pub.scan_count < 3:
                time.sleep(0.05)
            assert pub.scan_count >= 3, "fused seam published no scans"
            msg = pub.scans[-1]
            assert np.isfinite(msg.ranges).any()
        finally:
            node.shutdown()
            sim.stop()

    def test_dummy_mode_falls_back_to_host(self):
        """The dummy driver synthesizes scans above the protocol layer:
        fused must fall back to the host path with the chain in place."""
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode
        from rplidar_ros2_driver_tpu.node.publisher import CollectingPublisher

        params = _params(dummy_mode=True, ingest_backend="fused")
        node = RPlidarNode(params, CollectingPublisher())
        try:
            assert node.configure()
            assert node.fused_ingest is None
            assert node.chain is not None
        finally:
            node.shutdown()
