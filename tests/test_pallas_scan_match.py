"""Bit-exact parity suite for the Pallas correlative-matcher kernels
(ops/pallas_scan_match.py vs the XLA arm vs the NumPy reference).

The contract under test is EQUALITY, not closeness: the matcher datapath
is int32 fixed point end to end, so the VMEM-tiled Pallas lowering
(interpret mode on this CPU backend — the exact code path a
pallas-pinned CPU config runs) must reproduce the XLA arm and
ops/scan_match_ref.py byte-for-byte — poses, scores, score volumes, and
final Q10 log-odds maps — across map geometries, fleet sizes,
degenerate scans, score ties, and the int32 score bound.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.mapping.mapper import (
    FleetMapper,
    map_config_from_params,
    resolve_match_backend,
)
from rplidar_ros2_driver_tpu.ops.scan_match import (
    PQ_LIMIT,
    SUB,
    MapConfig,
    MapState,
    map_match_step,
    match_scan,
    min_quant_shift,
    theta_offsets,
    update_map,
)
from rplidar_ros2_driver_tpu.ops.scan_match_ref import (
    create_map_state_np,
    map_match_step_np,
    match_scan_np,
    quantize_points_np,
    update_map_np,
)

pytestmark = pytest.mark.pallas

BEAMS = 192


def _cfg(grid: int = 64, beams: int = BEAMS, clamp_q: int = 8192,
         **kw) -> MapConfig:
    kw.setdefault("quant_shift", min_quant_shift(clamp_q, beams))
    return MapConfig(
        grid=grid, cell_m=0.1, beams=beams, clamp_q=clamp_q, **kw
    )


def _arms(cfg: MapConfig):
    """(xla_cfg, pallas_cfg) twins of one geometry."""
    return cfg, dataclasses.replace(cfg, match_backend="pallas")


def _rand_inputs(rng, cfg: MapConfig, beams: int):
    """Randomized fixed-point inputs: a structured-noise map (positive
    blobs so matches actually accept), a pose inside the translation
    clamp, and subcell endpoints spanning the whole quantization
    window including its edges."""
    g = cfg.grid
    lo = rng.integers(-cfg.clamp_q, cfg.clamp_q + 1, (g, g), np.int32)
    lo[rng.integers(0, g, g), rng.integers(0, g, g)] = cfg.clamp_q
    lim = cfg.t_limit_sub
    pose = np.asarray([
        rng.integers(-lim // 2, lim // 2),
        rng.integers(-lim // 2, lim // 2),
        rng.integers(0, cfg.theta_divisions),
    ], np.int32)
    span = min((g // 2) * SUB, PQ_LIMIT)
    pq = rng.integers(-span, span + 1, (beams, 2)).astype(np.int32)
    ok = rng.uniform(size=beams) > 0.15
    return lo, pose, pq, ok


def _assert_match_parity(lo, pose, pq, ok, cfg_x, cfg_p):
    """match_scan on both device arms + the numpy oracle: dpose, score
    and n_valid must be byte-equal."""
    dp_x, s_x, n_x = match_scan(lo, pose, pq, ok, cfg_x)
    dp_p, s_p, n_p = match_scan(lo, pose, pq, ok, cfg_p)
    dp_n, s_n, n_n = match_scan_np(lo, pose, pq, ok, cfg_x)
    np.testing.assert_array_equal(np.asarray(dp_x), dp_n)
    np.testing.assert_array_equal(np.asarray(dp_p), dp_n)
    assert int(s_x) == int(s_n) == int(s_p)
    assert int(n_x) == int(n_n) == int(n_p)
    return dp_n, int(s_n)


def _assert_update_parity(lo, pose, pq, ok, cfg_x, cfg_p):
    up_x = np.asarray(update_map(lo, pose, pq, ok, cfg_x))
    up_p = np.asarray(update_map(lo, pose, pq, ok, cfg_p))
    up_n = update_map_np(lo, pose, pq, ok, cfg_x)
    np.testing.assert_array_equal(up_x, up_n)
    np.testing.assert_array_equal(up_p, up_n)
    return up_n


# ---------------------------------------------------------------------------
# randomized kernel parity across the MapConfig geometry range
# ---------------------------------------------------------------------------


class TestKernelParity:
    @pytest.mark.parametrize("grid", [8, 64, 256, 1024])
    def test_match_scan_bit_exact_across_grids(self, grid):
        """Every grid size class the MapConfig validation range admits:
        the minimum (8), the defaults' neighborhood, and the maximum
        (1024) — each with randomized maps, poses and scans."""
        beams = 64 if grid >= 256 else BEAMS
        cfg_x, cfg_p = _arms(_cfg(grid=grid, beams=beams))
        rng = np.random.default_rng(grid)
        for trial in range(2 if grid >= 256 else 4):
            lo, pose, pq, ok = _rand_inputs(rng, cfg_x, beams)
            _assert_match_parity(lo, pose, pq, ok, cfg_x, cfg_p)

    @pytest.mark.parametrize("grid", [8, 64, 256])
    def test_update_map_bit_exact_across_grids(self, grid):
        beams = 64 if grid >= 256 else BEAMS
        cfg_x, cfg_p = _arms(_cfg(grid=grid, beams=beams))
        rng = np.random.default_rng(1000 + grid)
        for trial in range(3):
            lo, pose, pq, ok = _rand_inputs(rng, cfg_x, beams)
            up = _assert_update_parity(lo, pose, pq, ok, cfg_x, cfg_p)
            assert np.abs(up).max() <= cfg_x.clamp_q

    def test_update_map_matches_both_voxel_arms(self):
        """The Pallas update always uses the one-hot/matmul tiling; it
        must equal BOTH XLA voxel-kernel arms (scatter and matmul are
        already pinned equal to each other)."""
        cfg_s = _cfg(voxel_backend="scatter")
        cfg_m = dataclasses.replace(cfg_s, voxel_backend="matmul")
        cfg_p = dataclasses.replace(cfg_s, match_backend="pallas")
        rng = np.random.default_rng(7)
        lo, pose, pq, ok = _rand_inputs(rng, cfg_s, BEAMS)
        up_s = np.asarray(update_map(lo, pose, pq, ok, cfg_s))
        up_m = np.asarray(update_map(lo, pose, pq, ok, cfg_m))
        up_p = np.asarray(update_map(lo, pose, pq, ok, cfg_p))
        np.testing.assert_array_equal(up_s, up_m)
        np.testing.assert_array_equal(up_s, up_p)

    def test_free_samples_zero_skips_miss_pass(self):
        cfg_x, cfg_p = _arms(_cfg(free_samples=0))
        rng = np.random.default_rng(8)
        lo, pose, pq, ok = _rand_inputs(rng, cfg_x, BEAMS)
        up = _assert_update_parity(lo, pose, pq, ok, cfg_x, cfg_p)
        # no miss pass: nothing ever decrements below the prior value
        assert (up >= np.clip(lo, -cfg_x.clamp_q, cfg_x.clamp_q)).all()

    def test_explicit_interpret_matches_dispatch_resolution(self):
        """interpret=True pinned explicitly must equal the
        interpret=None lowering-dispatch resolution on this CPU-only
        process (the _lowering_dispatch contract for the matcher
        kernels)."""
        from rplidar_ros2_driver_tpu.ops.pallas_scan_match import (
            coarse_scores_pallas,
            log_odds_update_pallas,
        )
        import jax.numpy as jnp

        cfg_x, cfg_p = _arms(_cfg())
        rng = np.random.default_rng(9)
        lo, pose, pq, ok = _rand_inputs(rng, cfg_x, BEAMS)
        center = (cfg_p.grid // 2) * SUB
        posec = jnp.asarray(pose[:2] + center)
        trig = np.asarray([1 << 14, 0], np.int32)  # θ = 0
        for interp in (True, None):
            mq, sc = coarse_scores_pallas(
                jnp.asarray(lo), jnp.asarray(pq), jnp.asarray(ok), posec,
                jnp.asarray(trig[0]), jnp.asarray(trig[1]), cfg_p,
                interpret=interp,
            )
            up = log_odds_update_pallas(
                jnp.asarray(lo), jnp.asarray(pq), jnp.asarray(ok), posec,
                jnp.asarray(trig[0]), jnp.asarray(trig[1]), cfg_p,
                interpret=interp,
            )
            if interp is True:
                pinned = (np.asarray(mq), np.asarray(sc), np.asarray(up))
            else:
                np.testing.assert_array_equal(np.asarray(mq), pinned[0])
                np.testing.assert_array_equal(np.asarray(sc), pinned[1])
                np.testing.assert_array_equal(np.asarray(up), pinned[2])


# ---------------------------------------------------------------------------
# degenerate scans
# ---------------------------------------------------------------------------


class TestDegenerate:
    def test_all_invalid_scan(self):
        cfg_x, cfg_p = _arms(_cfg())
        rng = np.random.default_rng(11)
        lo, pose, pq, _ = _rand_inputs(rng, cfg_x, BEAMS)
        ok = np.zeros(BEAMS, bool)
        dp, score = _assert_match_parity(lo, pose, pq, ok, cfg_x, cfg_p)
        assert score == 0 and tuple(dp) == (0, 0, 0)
        up = _assert_update_parity(lo, pose, pq, ok, cfg_x, cfg_p)
        np.testing.assert_array_equal(
            up, np.clip(lo, -cfg_x.clamp_q, cfg_x.clamp_q)
        )

    def test_single_beam_scan(self):
        cfg_x, cfg_p = _arms(_cfg())
        rng = np.random.default_rng(12)
        lo, pose, pq, _ = _rand_inputs(rng, cfg_x, BEAMS)
        ok = np.zeros(BEAMS, bool)
        ok[0] = True
        _assert_match_parity(lo, pose, pq, ok, cfg_x, cfg_p)
        _assert_update_parity(lo, pose, pq, ok, cfg_x, cfg_p)

    def test_far_point_int32_wrap_guard(self):
        """Endpoints at the subcell clamp boundary (±PQ_LIMIT — the
        quantizer's int32-wrap guard): the rotated coordinates reach
        their extreme magnitudes and the off-map gathers must drop them
        identically on every arm, with no wrap divergence."""
        cfg_x, cfg_p = _arms(_cfg())
        pq = np.asarray(
            [[PQ_LIMIT, PQ_LIMIT], [-PQ_LIMIT, PQ_LIMIT],
             [PQ_LIMIT, -PQ_LIMIT], [-PQ_LIMIT, -PQ_LIMIT],
             [PQ_LIMIT, 0], [0, -PQ_LIMIT]] + [[0, 0]] * (BEAMS - 6),
            np.int32,
        )
        ok = np.ones(BEAMS, bool)
        rng = np.random.default_rng(13)
        lo = rng.integers(0, cfg_x.clamp_q + 1, (64, 64), np.int32)
        for th in (0, 137, 359):
            pose = np.asarray([0, 0, th], np.int32)
            _assert_match_parity(lo, pose, pq, ok, cfg_x, cfg_p)
            _assert_update_parity(lo, pose, pq, ok, cfg_x, cfg_p)

    def test_nonfinite_float_points_quantize_identically(self):
        """The float quantizer upstream of the kernels drops NaN/inf
        and out-of-window points BEFORE the cast; the full step (float
        points in) must stay bit-exact on the pallas arm too."""
        cfg_x, cfg_p = _arms(_cfg())
        pts = np.full((BEAMS, 2), np.inf, np.float32)
        pts[: BEAMS // 2] = np.nan
        mask = np.ones(BEAMS, bool)
        pq, ok = quantize_points_np(pts, mask, cfg_x)
        assert not ok.any()
        st_p = MapState.create(cfg_p)
        st_p, wire = map_match_step(st_p, pts, mask, np.int32(1), cfg=cfg_p)
        st_n, wire_n = map_match_step_np(
            create_map_state_np(cfg_x), pts, mask, 1, cfg_x
        )
        np.testing.assert_array_equal(np.asarray(wire), wire_n)
        assert np.count_nonzero(np.asarray(st_p.log_odds)) == 0


# ---------------------------------------------------------------------------
# score ties: first-max-wins argmax survives the tiling
# ---------------------------------------------------------------------------


class TestScoreTies:
    def test_uniform_map_picks_first_candidate_in_c_order(self):
        """A uniformly positive map scores EVERY candidate identically,
        so the winner is pure tie-break: flat index 0 of the coarse
        (U, V) plane, then flat index 0 of the fine (T, F, F) volume —
        i.e. u=-w, v=-w, θ=first offset, du=-r, dv=-r.  All three arms
        must agree on exactly that candidate."""
        cfg_x, cfg_p = _arms(_cfg())
        lo = np.full((64, 64), 4096, np.int32)
        # one central beam: its window gathers stay on-map for every
        # candidate shift, keeping the tie perfect
        pq = np.zeros((BEAMS, 2), np.int32)
        ok = np.zeros(BEAMS, bool)
        ok[0] = True
        pose = np.zeros(3, np.int32)
        dp, score = _assert_match_parity(lo, pose, pq, ok, cfg_x, cfg_p)
        assert score > 0
        w, r, c = cfg_x.window_cells, cfg_x.fine_radius, cfg_x.coarse
        dth = theta_offsets(cfg_x)
        expect = np.asarray(
            [(-w * c - r) * SUB, (-w * c - r) * SUB, dth[0]], np.int32
        )
        np.testing.assert_array_equal(dp, expect)

    def test_two_way_tie_earlier_flat_index_wins(self):
        """Two disjoint occupied blobs placed so two translation
        candidates score equally: the earlier flat index must win on
        every arm (a tiled lowering that reordered its reduction or
        argmax would flip this)."""
        cfg_x, cfg_p = _arms(_cfg())
        g, c = cfg_x.grid, cfg_x.coarse
        lo = np.zeros((g, g), np.int32)
        # symmetric pair around the beam's landing cell: candidates
        # +d and -d see mirror-identical mass
        center_cell = g // 2
        for d in (2, 6):
            lo[center_cell - d, center_cell] = 4096
            lo[center_cell + d, center_cell] = 4096
        pq = np.zeros((BEAMS, 2), np.int32)
        ok = np.zeros(BEAMS, bool)
        ok[0] = True
        pose = np.zeros(3, np.int32)
        dp_n, s_n, _ = match_scan_np(lo, pose, pq, ok, cfg_x)
        dp, score = _assert_match_parity(lo, pose, pq, ok, cfg_x, cfg_p)
        assert score == int(s_n) and score > 0
        # the accepted delta is the numpy oracle's first-max candidate
        np.testing.assert_array_equal(dp, dp_n)


# ---------------------------------------------------------------------------
# quant_shift boundary at the int32 score bound
# ---------------------------------------------------------------------------


class TestQuantShiftBoundary:
    def test_saturated_map_at_min_quant_shift_stays_exact(self):
        """clamp_q and beams chosen so min_quant_shift is the LAST
        shift keeping (clamp >> shift) * 1024 * beams under 2^31, the
        map saturated at clamp everywhere and every beam valid on one
        cell: scores sit near the int32 bound, where any extra or
        missing shift — or a 64-bit accumulation detour — would
        diverge.  All three arms must agree bit-for-bit."""
        beams, clamp_q = 2048, 16384
        shift = min_quant_shift(clamp_q, beams)
        assert shift > 0  # the bound is actually binding
        assert (clamp_q >> shift) * SUB * SUB * beams < 2**31
        assert (clamp_q >> (shift - 1)) * SUB * SUB * beams >= 2**31
        cfg_x = MapConfig(
            grid=64, cell_m=0.1, beams=beams, clamp_q=clamp_q,
            quant_shift=shift,
        )
        cfg_p = dataclasses.replace(cfg_x, match_backend="pallas")
        lo = np.full((64, 64), clamp_q, np.int32)
        pq = np.zeros((beams, 2), np.int32)  # all beams on the centre
        ok = np.ones(beams, bool)
        pose = np.zeros(3, np.int32)
        dp, score = _assert_match_parity(lo, pose, pq, ok, cfg_x, cfg_p)
        # every gather corner hits clamp>>shift with full Σw weight
        assert score == (clamp_q >> shift) * SUB * SUB * beams
        _assert_update_parity(lo, pose, pq, ok, cfg_x, cfg_p)


# ---------------------------------------------------------------------------
# fleet-level parity through the mapper (vmapped dispatch + checkpoint)
# ---------------------------------------------------------------------------


def _params(**kw) -> DriverParams:
    base = dict(
        dummy_mode=True,
        filter_backend="cpu",
        filter_chain=("clip", "median", "voxel"),
        map_enable=True,
        map_backend="host",
        map_grid=64,
        map_cell_m=0.1,
    )
    base.update(kw)
    return DriverParams(**base)


def _room_points(pose_xyt, n: int, half: float = 2.5):
    t = np.linspace(0, 2 * np.pi, n, endpoint=False)
    dx, dy = np.cos(t), np.sin(t)
    with np.errstate(divide="ignore"):
        r = np.minimum(
            np.where(np.abs(dx) > 1e-12, half / np.abs(dx), np.inf),
            np.where(np.abs(dy) > 1e-12, half / np.abs(dy), np.inf),
        )
    wx, wy = dx * r, dy * r
    x0, y0, th = pose_xyt
    c, s = np.cos(-th), np.sin(-th)
    px = c * (wx - x0) - s * (wy - y0)
    py = s * (wx - x0) + c * (wy - y0)
    return np.stack([px, py], 1).astype(np.float32), np.ones(n, bool)


def _fleet_inputs(streams: int, tick: int, beams: int):
    pts = np.zeros((streams, beams, 2), np.float32)
    masks = np.zeros((streams, beams), bool)
    live = np.zeros((streams,), np.int32)
    for s in range(streams):
        if (tick + s) % 4 == 3:
            continue  # idle this tick
        pose = (0.04 * tick * (1 + 0.3 * s), -0.03 * tick, 0.003 * tick)
        p, m = _room_points(pose, beams)
        rng = np.random.default_rng(100 * s + tick)
        m &= rng.uniform(size=beams) > 0.1
        pts[s], masks[s] = p, m
        live[s] = 1
    return pts, masks, live


class TestFleetParity:
    @pytest.mark.parametrize("streams", [1, 3, 8])
    def test_pallas_fleet_bit_exact_vs_host_with_restore(self, streams):
        """The acceptance bar: fused+pallas fleets 1/3/8 vs N numpy host
        steps, byte-equal estimates and final maps, INCLUDING a
        snapshot/restore cycle mid-run (the restored mapper must resume
        on the same byte trajectory)."""
        beams = 128
        host = FleetMapper(_params(), streams, beams=beams)
        pal = FleetMapper(
            _params(map_backend="fused", match_backend="pallas"),
            streams, beams=beams,
        )
        assert pal.cfg.match_backend == "pallas"
        for tick in range(3):
            pts, masks, live = _fleet_inputs(streams, tick, beams)
            eh = host.submit_points(pts, masks, live)
            ep = pal.submit_points(pts, masks, live)
            for s in range(streams):
                if eh[s] is None:
                    assert ep[s] is None
                    continue
                np.testing.assert_array_equal(eh[s].pose_q, ep[s].pose_q)
                assert eh[s].score == ep[s].score
                assert eh[s].matched_points == ep[s].matched_points
        # snapshot/restore cycle: resume and stay on the byte trajectory
        snap = pal.snapshot()
        resumed = FleetMapper(
            _params(map_backend="fused", match_backend="pallas"),
            streams, beams=beams,
        )
        assert resumed.restore(snap) is True
        for tick in range(3, 5):
            pts, masks, live = _fleet_inputs(streams, tick, beams)
            eh = host.submit_points(pts, masks, live)
            er = resumed.submit_points(pts, masks, live)
            for s in range(streams):
                if eh[s] is not None:
                    np.testing.assert_array_equal(
                        eh[s].pose_q, er[s].pose_q
                    )
        sh, sr = host.snapshot(), resumed.snapshot()
        for k in sh:
            np.testing.assert_array_equal(sh[k], sr[k])
        assert resumed.dispatch_count == 2  # one vmapped dispatch per tick

    def test_pallas_vs_xla_fused_identical_programs(self):
        """fused+xla and fused+pallas land identical wires and maps over
        the same tick stream (the two device arms of bench config 14)."""
        beams = 128
        fx = FleetMapper(
            _params(map_backend="fused", match_backend="xla"), 2,
            beams=beams,
        )
        fp = FleetMapper(
            _params(map_backend="fused", match_backend="pallas"), 2,
            beams=beams,
        )
        for tick in range(4):
            pts, masks, live = _fleet_inputs(2, tick, beams)
            ex = fx.submit_points(pts, masks, live)
            ep = fp.submit_points(pts, masks, live)
            for s in range(2):
                if ex[s] is not None:
                    np.testing.assert_array_equal(ex[s].pose_q, ep[s].pose_q)
        sx, sp = fx.snapshot(), fp.snapshot()
        for k in sx:
            np.testing.assert_array_equal(sx[k], sp[k])


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


class TestSeam:
    def test_resolver(self):
        assert resolve_match_backend("auto") == "xla"
        assert resolve_match_backend("auto", "tpu") == "xla"  # clamped
        assert resolve_match_backend("pallas") == "pallas"
        assert resolve_match_backend("xla", "cpu") == "xla"

    def test_params_flow_to_map_config(self):
        cfg = map_config_from_params(_params(match_backend="pallas"), 128)
        assert cfg.match_backend == "pallas"
        cfg = map_config_from_params(_params(), 128)
        assert cfg.match_backend == "xla"  # auto resolves clamped

    def test_param_validation(self):
        _params(match_backend="pallas").validate()
        with pytest.raises(ValueError, match="match_backend"):
            _params(match_backend="mosaic").validate()
        with pytest.raises(ValueError, match="match_backend"):
            MapConfig(match_backend="auto")  # must be resolved by then
