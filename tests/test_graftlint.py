"""graftlint self-tests: every rule fires on its positive fixture and
stays quiet on the negative twin, suppressions/markers behave, baseline
reconciliation is exact — and the REAL repo lints clean (the tier-1
gate that keeps the invariants enforced, not aspirational)."""

from __future__ import annotations

import json
import textwrap

import pytest

from rplidar_ros2_driver_tpu.tools.graftlint import load_config, run_lint

BASE_CONFIG = """
[tool.graftlint]
paths = ["pkg"]
static_params = ["cfg", "config", "self"]

[tool.graftlint.gl004]
zones = ["pkg/zone.py"]
int_returning = ["int_fn"]
int_names = ["counts_i"]
float_names = ["fx", "meta"]
bool_names = ["ok"]

[tool.graftlint.gl007]
files = ["pkg/hot.py"]

[tool.graftlint.gl008]
bench = "bench.py"
bench_meta_test = "tests/test_bench_meta.py"
params_module = "pkg/config.py"
params_yaml = "param.yaml"
unvalidated_params_ok = ["name"]
precompile_exempt = []
"""


def _lint(tmp_path, files: dict, config: str = BASE_CONFIG):
    (tmp_path / "pyproject.toml").write_text(config)
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    findings, new, stale = run_lint(str(tmp_path))
    return findings


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# GL001 — host syncs inside jit
# ---------------------------------------------------------------------------


class TestGL001:
    def test_fires_on_np_asarray_and_item_in_jit(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                y = np.asarray(x)
                return x + y.item()
        """})
        msgs = [f.message for f in fs if f.rule == "GL001"]
        assert any("np.asarray" in m for m in msgs)
        assert any(".item()" in m for m in msgs)

    def test_fires_on_float_of_traced_param(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """})
        assert any(
            f.rule == "GL001" and "float(x)" in f.message for f in fs
        )

    def test_quiet_on_host_function_and_scalar_params(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax
            import numpy as np

            def host_parse(res):
                return np.asarray(res)  # not jit-reachable

            @jax.jit
            def f(x, n: int):
                return x * int(n)
        """})
        assert "GL001" not in _rules(fs)

    def test_suppression_with_reason_works(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                # graftlint: disable=GL001 — fixture-sanctioned host sync
                return np.asarray(x)
        """})
        assert "GL001" not in _rules(fs)

    def test_suppression_without_reason_is_ignored(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                # graftlint: disable=GL001
                return np.asarray(x)
        """})
        assert "GL001" in _rules(fs)


# ---------------------------------------------------------------------------
# GL002 — Python branching on traced values
# ---------------------------------------------------------------------------


class TestGL002:
    def test_fires_on_if_over_traced_comparison(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """})
        assert "GL002" in _rules(fs)

    def test_quiet_on_static_config_shape_and_none_checks(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax

            @jax.jit
            def f(x, ms, cfg):
                if cfg.enable:
                    x = x * 2
                if x.shape[0] > 4:
                    x = x[:4]
                if ms is None:
                    return x
                return x + ms
        """})
        assert "GL002" not in _rules(fs)

    def test_scalar_annotation_is_trusted(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax

            @jax.jit
            def f(x, n: int):
                while n < 4:
                    n *= 2
                return x * n
        """})
        assert "GL002" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL003 — donation hygiene
# ---------------------------------------------------------------------------


class TestGL003:
    def test_fires_on_read_after_donation(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            def use(state, x):
                out = step(state, x)
                return out + state
        """})
        assert any(
            f.rule == "GL003" and "donated to step" in f.message for f in fs
        )

    def test_quiet_when_rebound(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            def use(state, x):
                state = step(state, x)
                state = step(state, x)
                return state
        """})
        assert "GL003" not in _rules(fs)

    def test_same_line_double_load_reports_not_crashes(self, tmp_path):
        # regression: two Loads of the donated name on ONE line used to
        # reach the AST nodes in the sort key (nodes don't compare) and
        # crash the whole run with TypeError
        fs = _lint(tmp_path, {"pkg/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            def use(state, x):
                out = step(state, x)
                return (state, state)
        """})
        assert any(
            f.rule == "GL003" and "donated to step" in f.message for f in fs
        )

    def test_fires_on_undonated_carry_entry_in_ops(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("cfg",))
            def step(state, x, cfg):
                return state + x
        """})
        assert any(
            f.rule == "GL003" and "without donate_argnums" in f.message
            for f in fs
        )

    def test_quiet_when_donated_or_justified(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            # graftlint: disable=GL003 — fixture-sanctioned debug API
            @jax.jit
            def debug_step(state, x):
                return state + x
        """})
        assert "GL003" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL004 — bit-exact zones
# ---------------------------------------------------------------------------


class TestGL004:
    def test_fires_on_float_reduction_and_unpoliced_cast(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/zone.py": """
            import jax.numpy as jnp

            def score(fx):
                total = jnp.sum(fx)
                return total.astype(jnp.int32)
        """})
        msgs = [f.message for f in fs if f.rule == "GL004"]
        assert any("reduction" in m for m in msgs)
        assert any("float→int cast" in m for m in msgs)

    def test_quiet_on_int_reduction_and_policed_cast(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/zone.py": """
            import jax.numpy as jnp

            def score(fx, ok):
                n = jnp.sum(ok.astype(jnp.int32))
                v = jnp.sum(int_fn(fx), axis=0)
                # graftlint: policed — fixture clamps fx upstream
                q = fx.astype(jnp.int32)
                return n + v + q

            def int_fn(fx):
                return (fx > 0).astype(jnp.int32)
        """})
        assert "GL004" not in _rules(fs)

    def test_zone_scoping(self, tmp_path):
        # identical float reduction OUTSIDE the declared zone: quiet
        fs = _lint(tmp_path, {"pkg/other.py": """
            import jax.numpy as jnp

            def score(fx):
                return jnp.sum(fx)
        """})
        assert "GL004" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL005 — weak-type promotion in zones
# ---------------------------------------------------------------------------


class TestGL005:
    def test_fires_on_bare_float_scalar(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/zone.py": """
            import jax.numpy as jnp

            def scale(fx):
                return fx * 0.5
        """})
        assert "GL005" in _rules(fs)

    def test_quiet_on_wrapped_scalar_and_int_literal(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/zone.py": """
            import jax.numpy as jnp

            def scale(fx):
                half = jnp.float32(0.5)
                return (fx * half + fx * jnp.float32(0.25)) * 2
        """})
        assert "GL005" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL006 — static_argnames hygiene
# ---------------------------------------------------------------------------


class TestGL006:
    def test_fires_on_mutable_static_value_and_unfrozen_config(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import dataclasses
            import functools
            import jax

            @dataclasses.dataclass
            class StepConfig:
                n: int = 4

            @functools.partial(jax.jit, static_argnames=("modes",))
            def f(x, modes):
                return x

            def call(x):
                return f(x, modes=[1, 2])
        """})
        msgs = [f.message for f in fs if f.rule == "GL006"]
        assert any("StepConfig" in m for m in msgs)
        assert any("mutable value" in m for m in msgs)

    def test_quiet_on_frozen_config_and_tuple(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import dataclasses
            import functools
            import jax

            @dataclasses.dataclass(frozen=True)
            class StepConfig:
                n: int = 4

            @functools.partial(jax.jit, static_argnames=("modes",))
            def f(x, modes):
                return x

            def call(x):
                return f(x, modes=(1, 2))
        """})
        assert "GL006" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL007 — hot-loop allocations
# ---------------------------------------------------------------------------


class TestGL007:
    def test_fires_inside_marked_region_only(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/hot.py": """
            import numpy as np

            # graftlint: hot-loop
            def dispatch(self, m):
                buf = np.zeros((m, 4), np.uint8)
                return buf

            def cold_setup(m):
                return np.zeros((m, 4), np.uint8)
        """})
        gl7 = [f for f in fs if f.rule == "GL007"]
        assert len(gl7) == 1 and "dispatch" not in gl7[0].message

    def test_def_marker_does_not_absorb_later_pairs_end(self, tmp_path):
        # regression: a def-scoped marker used to pair with ANY later
        # end-hot-loop, fusing everything between into one bogus region
        fs = _lint(tmp_path, {"pkg/hot.py": """
            import numpy as np

            # graftlint: hot-loop
            def dispatch(self, m):
                return m + 1

            def unrelated(m):
                return np.zeros((m,), np.uint8)  # NOT hot: must stay quiet

            def other(self, m, raw):
                # graftlint: hot-loop
                view = np.frombuffer(raw, np.uint8)
                # graftlint: end-hot-loop
                return view
        """})
        assert "GL007" not in _rules(fs)

    def test_region_markers_and_frombuffer_ok(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/hot.py": """
            import numpy as np

            def dispatch(self, m, raw):
                # graftlint: hot-loop
                view = np.frombuffer(raw, np.uint8)
                out = view.reshape(m, 4)
                # graftlint: end-hot-loop
                scratch = np.zeros((m,), np.uint8)
                return out, scratch
        """})
        assert "GL007" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL008 — structural consistency
# ---------------------------------------------------------------------------


class TestGL008:
    def test_bench_pin_and_param_drift_fire(self, tmp_path):
        fs = _lint(tmp_path, {
            "bench.py": """
                GRADED = {1: ("chain", 100, {}), 2: ("e2e", 100, {})}
            """,
            "tests/test_bench_meta.py": """
                def test_names():
                    assert metric_name(1) == "one"
            """,
            "pkg/config.py": """
                import dataclasses

                @dataclasses.dataclass
                class DriverParams:
                    name: str = "x"
                    rate: int = 7
                    ghost: int = 1

                    def validate(self):
                        if self.rate < 0:
                            raise ValueError("rate")
            """,
            "param.yaml": """
                name: x
                rate: 7
                stale_key: true
            """,
        })
        msgs = [f.message for f in fs if f.rule == "GL008"]
        assert any("metric_name(2)" in m for m in msgs)
        assert any("DriverParams.ghost" in m for m in msgs)  # not in yaml
        assert any("never validated" in m and "ghost" in m for m in msgs)
        assert any("stale_key" in m for m in msgs)

    def test_precompile_reachability(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def warmed(state, x):
                return state + x

            @functools.partial(jax.jit, donate_argnums=(0,))
            def cold(state, x):
                return state - x

            def precompile():
                warmed(0, 1)
        """})
        gl8 = [f.message for f in fs if f.rule == "GL008"]
        assert any("cold" in m for m in gl8)
        assert not any("warmed" in m for m in gl8)


# ---------------------------------------------------------------------------
# GL009 — unbounded retry loops
# ---------------------------------------------------------------------------


class TestGL009:
    def test_fires_on_constant_sleep_retry_loop(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def reconnect(dev):
                while True:
                    if dev.connect():
                        break
                    time.sleep(1.0)
        """})
        msgs = [f.message for f in fs if f.rule == "GL009"]
        assert len(msgs) == 1 and "unbounded retry loop" in msgs[0]

    def test_quiet_on_computed_backoff(self, tmp_path):
        # non-constant sleep argument = a computed backoff: absolved
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def reconnect(dev, policy):
                while True:
                    if dev.connect():
                        break
                    time.sleep(policy.next_delay())
        """})
        assert "GL009" not in _rules(fs)

    def test_quiet_on_attempt_cap_and_deadline(self, tmp_path):
        # comparison-gated escapes (attempt cap, deadline) are the bound
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def capped(dev):
                attempt = 0
                while True:
                    if dev.connect():
                        break
                    attempt += 1
                    if attempt >= 5:
                        raise RuntimeError("gave up")
                    time.sleep(1.0)

            def deadlined(dev, deadline):
                while True:
                    if dev.connect():
                        return True
                    if time.monotonic() > deadline:
                        return False
                    time.sleep(1.0)
        """})
        assert "GL009" not in _rules(fs)

    def test_quiet_on_bounded_while_condition(self, tmp_path):
        # not `while True`: the loop condition itself is the bound
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def run(self):
                while self._running.is_set():
                    self.poll()
                    time.sleep(0.2)
        """})
        assert "GL009" not in _rules(fs)

    def test_suppression_with_reason_works(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def watchdog(dev):
                # graftlint: disable=GL009 — fixture-sanctioned daemon poll
                while True:
                    dev.kick()
                    time.sleep(5.0)
        """})
        assert "GL009" not in _rules(fs)

    def test_closure_in_method_reports_once(self, tmp_path):
        # regression: the nested-def skip used split('.')[0], so a
        # retry loop in a closure inside a METHOD was reported twice
        # (once per qualname walk) — unbaselineable, since the two
        # messages differ
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            class Node:
                def start(self, dev):
                    def worker():
                        while True:
                            if dev.connect():
                                break
                            time.sleep(1.0)
                    return worker
        """})
        gl9 = [f for f in fs if f.rule == "GL009"]
        assert len(gl9) == 1, [f.message for f in gl9]

    def test_baseline_reconcile_covers_gl009(self, tmp_path):
        """A baselined GL009 finding passes; a stale GL009 entry fails
        (the same exact-description contract every rule carries)."""
        src = {"pkg/m.py": """
            import time

            def reconnect(dev):
                while True:
                    if dev.connect():
                        break
                    time.sleep(1.0)
        """}
        (tmp_path / "pyproject.toml").write_text(BASE_CONFIG)
        for rel, body in src.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(body))
        findings, new, stale = run_lint(str(tmp_path))
        target = [f for f in findings if f.rule == "GL009"][0]
        (tmp_path / "graftlint.baseline.json").write_text(json.dumps({
            "findings": [{
                "rule": target.rule, "path": target.path,
                "message": target.message,
                "justification": "fixture: legacy loop, fix queued",
            }, {
                "rule": "GL009", "path": "pkg/gone.py",
                "message": "no longer fires",
                "justification": "stale entry",
            }]
        }))
        findings, new, stale = run_lint(str(tmp_path))
        assert not any(f.key() == target.key() for f in new)
        assert len(stale) == 1 and stale[0]["path"] == "pkg/gone.py"


# ---------------------------------------------------------------------------
# GL010 — pallas_call must ride the compiled-vs-interpret selector
# ---------------------------------------------------------------------------


_GL010_GOOD = """
    import functools
    import jax
    from jax.experimental import pallas as pl

    def _lowering_dispatch(compiled_fn, interpret_fn, *args):
        return jax.lax.platform_dependent(
            *args, tpu=compiled_fn, default=interpret_fn
        )

    def _kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:]

    def _call(x, interpret):
        return pl.pallas_call(_kernel, interpret=interpret)(x)

    def entry(x, interpret=None):
        if interpret is None:
            return _lowering_dispatch(
                functools.partial(_call, interpret=False),
                functools.partial(_call, interpret=True),
                x,
            )
        return _call(x, interpret)
"""


class TestGL010:
    def test_fires_on_missing_interpret_kwarg(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                return pl.pallas_call(_kernel)(x)
        """})
        msgs = [f.message for f in fs if f.rule == "GL010"]
        assert len(msgs) == 1 and "no `interpret=`" in msgs[0]

    def test_fires_on_constant_interpret(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                return pl.pallas_call(_kernel, interpret=False)(x)
        """})
        msgs = [f.message for f in fs if f.rule == "GL010"]
        assert len(msgs) == 1 and "constant" in msgs[0]

    def test_fires_on_computed_interpret(self, tmp_path):
        """The lowering choice computed in place (process default
        backend — the exact bug _lowering_dispatch exists to prevent)
        is no better than a constant."""
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            import jax
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                interp = jax.default_backend() != "tpu"
                return pl.pallas_call(_kernel, interpret=interp)(x)
        """})
        msgs = [f.message for f in fs if f.rule == "GL010"]
        assert len(msgs) == 1 and "not a parameter" in msgs[0]

    def test_fires_without_module_selector(self, tmp_path):
        """interpret threaded as a parameter but no _lowering_dispatch
        anywhere in the module: nothing sanctioned ever supplies it."""
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x, interpret):
                return pl.pallas_call(_kernel, interpret=interpret)(x)
        """})
        msgs = [f.message for f in fs if f.rule == "GL010"]
        assert len(msgs) == 1 and "_lowering_dispatch" in msgs[0]

    def test_quiet_on_the_sanctioned_pattern(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": _GL010_GOOD})
        assert "GL010" not in _rules(fs)

    def test_quiet_on_imported_selector(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from pkg.ops.base import _lowering_dispatch
            import functools
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def _call(x, interpret):
                return pl.pallas_call(_kernel, interpret=interpret)(x)

            def entry(x):
                return _lowering_dispatch(
                    functools.partial(_call, interpret=False),
                    functools.partial(_call, interpret=True),
                    x,
                )
        """, "pkg/ops/base.py": """
            import jax

            def _lowering_dispatch(compiled_fn, interpret_fn, *args):
                return jax.lax.platform_dependent(
                    *args, tpu=compiled_fn, default=interpret_fn
                )
        """})
        assert "GL010" not in _rules(fs)

    def test_quiet_outside_ops(self, tmp_path):
        """The rule polices ops/ — a bench-local experiment kernel is
        not a production lowering."""
        fs = _lint(tmp_path, {"pkg/scratch.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                return pl.pallas_call(_kernel, interpret=False)(x)
        """})
        assert "GL010" not in _rules(fs)

    def test_suppression_with_reason_works(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                # graftlint: disable=GL010 — fixture-sanctioned TPU-only tool
                return pl.pallas_call(_kernel, interpret=False)(x)
        """})
        assert "GL010" not in _rules(fs)

    def test_baseline_reconcile_covers_gl010(self, tmp_path):
        src = {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                return pl.pallas_call(_kernel)(x)
        """}
        (tmp_path / "pyproject.toml").write_text(BASE_CONFIG)
        for rel, body in src.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(body))
        findings, new, stale = run_lint(str(tmp_path))
        target = [f for f in findings if f.rule == "GL010"][0]
        (tmp_path / "graftlint.baseline.json").write_text(json.dumps({
            "findings": [{
                "rule": target.rule, "path": target.path,
                "message": target.message,
                "justification": "fixture: port to selector queued",
            }, {
                "rule": "GL010", "path": "pkg/ops/gone.py",
                "message": "no longer fires",
                "justification": "stale entry",
            }]
        }))
        findings, new, stale = run_lint(str(tmp_path))
        assert not any(f.key() == target.key() for f in new)
        assert len(stale) == 1 and stale[0]["path"] == "pkg/ops/gone.py"


# ---------------------------------------------------------------------------
# baseline reconciliation
# ---------------------------------------------------------------------------


class TestBaseline:
    def test_baselined_finding_passes_and_stale_fails(self, tmp_path):
        files = {"pkg/zone.py": """
            import jax.numpy as jnp

            def scale(fx):
                return fx * 0.5
        """}
        (tmp_path / "pyproject.toml").write_text(BASE_CONFIG)
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        findings, new, stale = run_lint(str(tmp_path))
        target = [f for f in findings if f.rule == "GL005"][0]
        baseline = {
            "findings": [{
                "rule": target.rule, "path": target.path,
                "message": target.message,
                "justification": "fixture: known weak-type site",
            }, {
                "rule": "GL001", "path": "pkg/zone.py",
                "message": "no longer fires",
                "justification": "stale entry",
            }]
        }
        (tmp_path / "graftlint.baseline.json").write_text(
            json.dumps(baseline)
        )
        findings, new, stale = run_lint(str(tmp_path))
        assert not any(f.key() == target.key() for f in new)
        assert len(stale) == 1 and stale[0]["message"] == "no longer fires"

    def test_baseline_entry_requires_justification(self, tmp_path):
        (tmp_path / "pyproject.toml").write_text(BASE_CONFIG)
        (tmp_path / "pkg").mkdir()
        (tmp_path / "pkg" / "m.py").write_text("x = 1\n")
        (tmp_path / "graftlint.baseline.json").write_text(json.dumps({
            "findings": [{"rule": "GL001", "path": "a", "message": "b"}]
        }))
        with pytest.raises(ValueError, match="justification"):
            run_lint(str(tmp_path))


# ---------------------------------------------------------------------------
# GL011 — fixed-point overflow prover
# ---------------------------------------------------------------------------

GL011_CONFIG = """
[tool.graftlint]
paths = ["pkg"]
static_params = ["cfg", "self"]

[tool.graftlint.gl004]
zones = []
int_names = ["d_q2", "rate_q8", "steps"]

[tool.graftlint.gl011]
zones = ["pkg/fx.py", "pkg/fx_ok.py"]
sum_elems_default = 16384

[tool.graftlint.gl011.sum_elems]
"pkg/fx_ok.py" = 1024

[tool.graftlint.gl011.bounds]
d_q2 = [0, 262143]
rate_q8 = [-32768, 32767]
"""


class TestGL011:
    def test_fires_on_unprovable_product(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/fx.py": """
            def scale(d_q2, rate_q8):
                return d_q2 * rate_q8
        """}, config=GL011_CONFIG)
        mine = [f for f in fs if f.rule == "GL011"]
        assert any("not provably inside int32" in f.message for f in mine)
        # the witness is the interval trace: operands and result range
        assert any("∈" in (f.witness or "") for f in mine)

    def test_quiet_when_clamp_is_visible(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/fx.py": """
            import jax.numpy as jnp

            def scale(d_q2, rate_q8):
                r = jnp.clip(rate_q8, -128, 127)
                return d_q2 * r
        """}, config=GL011_CONFIG)
        assert "GL011" not in _rules(fs)

    def test_fires_on_undeclared_int_entry_param(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/fx.py": """
            def advance(steps):
                return steps + 1
        """}, config=GL011_CONFIG)
        assert any(
            f.rule == "GL011" and "`steps`" in f.message
            and "no declared bound" in f.message for f in fs
        )

    def test_fires_when_assignment_escapes_declared_bound(self, tmp_path):
        # the dth-shape bug: a declared name rebound to a derivably
        # WIDER value poisons every proof that consumes the declaration
        fs = _lint(tmp_path, {"pkg/fx.py": """
            def rebind(d_q2, rate_q8):
                rate_q8 = d_q2 * 64
                return rate_q8
        """}, config=GL011_CONFIG)
        assert any(
            f.rule == "GL011" and "escapes its declared bound" in f.message
            for f in fs
        )

    def test_escape_quiet_when_clamped(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/fx.py": """
            import jax.numpy as jnp

            def rebind(d_q2, rate_q8):
                rate_q8 = jnp.clip(d_q2 * 64, -32768, 32767)
                return rate_q8
        """}, config=GL011_CONFIG)
        assert "GL011" not in _rules(fs)

    def test_sum_reduce_uses_per_zone_element_cap(self, tmp_path):
        # identical source; fx.py uses the 16384 default (sum escapes
        # int32), fx_ok.py's declared 1024-element cap proves it
        src = """
            import jax.numpy as jnp

            def fold(d_q2):
                return jnp.sum(d_q2)
        """
        fs = _lint(
            tmp_path, {"pkg/fx.py": src, "pkg/fx_ok.py": src},
            config=GL011_CONFIG,
        )
        mine = [f for f in fs if f.rule == "GL011"]
        assert [f.path for f in mine] == ["pkg/fx.py"]
        assert "sum-reduce" in mine[0].message
        assert "elements" in (mine[0].witness or "")

    def test_suppression_with_reason_works(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/fx.py": """
            def scale(d_q2, rate_q8):
                # graftlint: disable=GL011 — fixture-sanctioned wrap
                return d_q2 * rate_q8
        """}, config=GL011_CONFIG)
        assert "GL011" not in _rules(fs)

    def test_baseline_reconcile_covers_gl011(self, tmp_path):
        (tmp_path / "pyproject.toml").write_text(GL011_CONFIG)
        (tmp_path / "pkg").mkdir()
        (tmp_path / "pkg" / "fx.py").write_text(textwrap.dedent("""
            def scale(d_q2, rate_q8):
                return d_q2 * rate_q8
        """))
        findings, new, stale = run_lint(str(tmp_path))
        target = [f for f in findings if f.rule == "GL011"][0]
        (tmp_path / "graftlint.baseline.json").write_text(json.dumps({
            "findings": [{
                "rule": target.rule, "path": target.path,
                "message": target.message,
                "justification": "fixture: known wrap site",
            }]
        }))
        findings, new, stale = run_lint(str(tmp_path))
        assert not any(f.rule == "GL011" for f in new)
        assert stale == []
        # fix the code -> the baseline entry must go stale and FAIL
        (tmp_path / "pkg" / "fx.py").write_text(textwrap.dedent("""
            def scale(d_q2, rate_q8):
                return d_q2 + rate_q8
        """))
        findings, new, stale = run_lint(str(tmp_path))
        assert len(stale) == 1 and stale[0]["rule"] == "GL011"


# ---------------------------------------------------------------------------
# GL012 — lock-discipline race detector
# ---------------------------------------------------------------------------

# the PR 6 tear, distilled: _send reachable from BOTH sim threads,
# writing shared tx state with no lock — the bug a live-wire drive
# caught at runtime, now caught at parse time
SEND_TEAR_SRC = """
    import threading

    class SimDevice:
        def __init__(self):
            self._tx_lock = threading.Lock()
            self._tx_buf = b""

        def start(self):
            t = threading.Thread(target=self._rx_loop, daemon=True)
            t.start()
            s = threading.Thread(target=self._stream_loop, daemon=True)
            s.start()

        def _send(self, payload):
            self._tx_buf = payload

        def _rx_loop(self):
            self._send(b"descriptor")

        def _stream_loop(self):
            self._send(b"scan")
"""

GL012_LOCKED_CONFIG = BASE_CONFIG + """
[tool.graftlint.locks.SimDevice]
_tx_lock = ["_tx_buf"]
"""


class TestGL012:
    def test_pr6_send_tear_refires(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/dev.py": SEND_TEAR_SRC})
        mine = [f for f in fs if f.rule == "GL012"]
        assert any(
            "self._tx_buf of SimDevice" in f.message
            and "no declared lock" in f.message for f in mine
        )
        # the witness names the write site and its execution contexts
        assert any("_rx_loop" in (f.witness or "")
                   or "_stream_loop" in (f.witness or "") for f in mine)

    def test_declared_lock_must_be_held_at_the_write(self, tmp_path):
        # declaring the lock is not enough: the unheld write still fires
        fs = _lint(
            tmp_path, {"pkg/dev.py": SEND_TEAR_SRC},
            config=GL012_LOCKED_CONFIG,
        )
        assert any(
            f.rule == "GL012"
            and "without holding _tx_lock" in f.message for f in fs
        )

    def test_quiet_when_declared_lock_is_held(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/dev.py": SEND_TEAR_SRC.replace(
            "            self._tx_buf = payload",
            "            with self._tx_lock:\n"
            "                self._tx_buf = payload",
        )}, config=GL012_LOCKED_CONFIG)
        assert "GL012" not in _rules(fs)

    def test_single_context_field_needs_no_lock(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/dev.py": """
            import threading

            class Dev:
                def __init__(self):
                    self._t = None

                def start(self):
                    self._t = threading.Thread(target=self._loop)
                    self._t.start()

                def _loop(self):
                    pass
        """})
        assert "GL012" not in _rules(fs)

    def test_lock_order_cycle_fires(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/dev.py": """
            import threading

            class AB:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def f(self):
                    with self._a:
                        with self._b:
                            pass

                def g(self):
                    with self._b:
                        with self._a:
                            pass
        """})
        assert any(
            f.rule == "GL012"
            and "acquisition-order cycle" in f.message for f in fs
        )

    def test_suppression_with_reason_works(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/dev.py": SEND_TEAR_SRC.replace(
            "            self._tx_buf = payload",
            "            # graftlint: disable=GL012 — fixture-sanctioned"
            " tear\n"
            "            self._tx_buf = payload",
        )})
        assert "GL012" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL013 — zero-dispatch read-path prover
# ---------------------------------------------------------------------------


class TestGL013:
    def test_fires_on_dispatching_call_with_path_witness(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/serve.py": """
            import jax.numpy as jnp
            import numpy as np

            # graftlint: read-path
            def read_grid(snap):
                return helper(snap)

            def helper(snap):
                return jnp.asarray(snap.grid)
        """})
        mine = [f for f in fs if f.rule == "GL013"]
        assert any("jnp.asarray" in f.message for f in mine)
        # the witness is the call path from the marked root
        assert any("read_grid -> helper" in (f.witness or "") for f in mine)

    def test_fires_when_path_reaches_a_jitted_fn(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/serve.py": """
            import jax

            @jax.jit
            def fetch(grid):
                return grid + 1

            # graftlint: read-path
            def read_grid(snap):
                return fetch(snap.grid)
        """})
        assert any(
            f.rule == "GL013"
            and "jitted fetch is reachable" in f.message for f in fs
        )

    def test_quiet_on_pure_host_read_path(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/serve.py": """
            import numpy as np

            # graftlint: read-path
            def read_grid(snap):
                return np.repeat(snap.values, snap.runs)
        """})
        assert "GL013" not in _rules(fs)

    def test_unmarked_dispatch_is_not_a_read_path_finding(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/serve.py": """
            import jax.numpy as jnp

            def hot_path(x):
                return jnp.asarray(x)
        """})
        assert "GL013" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL008 bench-window hygiene + the TimedWindow seam itself
# ---------------------------------------------------------------------------


class TestBenchWindow:
    def test_raw_division_headline_fires(self, tmp_path):
        fs = _lint(tmp_path, {
            "bench.py": """
                import time

                GRADED = {}

                def bench_x():
                    t0 = time.perf_counter()
                    n = 100
                    dt = time.perf_counter() - t0
                    return {"metric": "m", "value": n / dt,
                            "unit": "scans/s"}
            """,
            "pkg/m.py": "x = 1\n",
        })
        assert any(
            f.rule == "GL008"
            and "TimedWindow.rate()" in f.message for f in fs
        )

    def test_rate_through_assign_chain_is_quiet(self, tmp_path):
        fs = _lint(tmp_path, {
            "bench.py": """
                GRADED = {}

                def bench_y(win):
                    sps = win.rate()
                    return {"metric": "m", "value": round(sps, 2),
                            "unit": "scans/s",
                            "vs_baseline": round(sps / 10.0, 3)}
            """,
            "pkg/m.py": "x = 1\n",
        })
        assert not any(
            f.rule == "GL008" and "TimedWindow" in f.message for f in fs
        )

    def test_timed_window_live_and_paired(self):
        from bench import TimedWindow

        win = TimedWindow()
        with win:
            pass
        win.add(10).add(5)
        assert win.count == 15
        assert win.rate() == 15 / max(win.seconds, 1e-9)
        assert TimedWindow.paired(300, 2.0).rate() == pytest.approx(150.0)

    def test_timed_window_guards_misuse(self):
        from bench import TimedWindow

        win = TimedWindow().start()
        with pytest.raises(RuntimeError):
            win.rate()  # still running
        with pytest.raises(RuntimeError):
            win.start()  # double start
        win.stop()
        with pytest.raises(RuntimeError):
            win.stop()  # double stop


# ---------------------------------------------------------------------------
# --explain: rationale + concrete witnesses
# ---------------------------------------------------------------------------


class TestExplain:
    def _tree(self, tmp_path, files, config):
        (tmp_path / "pyproject.toml").write_text(config)
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))

    def test_explain_gl011_prints_interval_witness(self, tmp_path, capsys):
        from rplidar_ros2_driver_tpu.tools.graftlint.runner import main

        self._tree(tmp_path, {"pkg/fx.py": """
            def scale(d_q2, rate_q8):
                return d_q2 * rate_q8
        """}, GL011_CONFIG)
        rc = main(["--explain", "GL011", "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0  # informational, never gates
        assert "fixed-point overflow prover" in out
        assert "witness:" in out and "∈" in out

    def test_explain_gl012_prints_write_pair(self, tmp_path, capsys):
        from rplidar_ros2_driver_tpu.tools.graftlint.runner import main

        self._tree(tmp_path, {"pkg/dev.py": SEND_TEAR_SRC}, BASE_CONFIG)
        rc = main(["--explain", "GL012", "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "lock-discipline race detector" in out
        assert "witness:" in out and "contexts:" in out

    def test_explain_gl013_prints_call_path(self, tmp_path, capsys):
        from rplidar_ros2_driver_tpu.tools.graftlint.runner import main

        self._tree(tmp_path, {"pkg/serve.py": """
            import jax.numpy as jnp

            # graftlint: read-path
            def read_grid(snap):
                return jnp.asarray(snap.grid)
        """}, BASE_CONFIG)
        rc = main(["--explain", "GL013", "--root", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "zero-dispatch read-path prover" in out
        assert "witness:" in out and "jnp.asarray()" in out

    def test_explain_unknown_rule_errors(self, tmp_path, capsys):
        from rplidar_ros2_driver_tpu.tools.graftlint.runner import main

        self._tree(tmp_path, {"pkg/m.py": "x = 1\n"}, BASE_CONFIG)
        assert main(["--explain", "GL999", "--root", str(tmp_path)]) == 2


# ---------------------------------------------------------------------------
# the repo itself
# ---------------------------------------------------------------------------


class TestRepoClean:
    def test_repo_lints_clean_with_all_rules_active(self):
        """The acceptance gate: the real tree has no unbaselined finding
        and no stale baseline entry, with every rule loaded."""
        from rplidar_ros2_driver_tpu.tools.graftlint.rules import ALL_RULES
        from rplidar_ros2_driver_tpu.tools.graftlint.runner import repo_root

        assert len(ALL_RULES) >= 13
        findings, new, stale = run_lint(repo_root())
        assert new == [], [f"{f.path}:{f.line} {f.rule} {f.message}"
                           for f in new]
        assert stale == []

    def test_repo_config_declares_zones_and_hot_files(self):
        from rplidar_ros2_driver_tpu.tools.graftlint.runner import repo_root

        cfg = load_config(repo_root())
        assert any("ops/ingest.py" in z for z in cfg.zones)
        assert any("ops/scan_match" in z for z in cfg.zones)
        assert any("driver/ingest.py" in h for h in cfg.hot_files)

    def test_repo_declares_prover_inputs(self):
        """The v2 rules are armed, not dormant: the real config carries
        GL011 bounds over the fixed-point zones, a GL012 lock map, and
        at least one marked GL013 read-path root."""
        from rplidar_ros2_driver_tpu.tools.graftlint.model import RepoIndex
        from rplidar_ros2_driver_tpu.tools.graftlint.runner import repo_root

        cfg = load_config(repo_root())
        assert any("ops/deskew.py" in z for z in cfg.gl011_zones)
        assert cfg.gl011_bound_map().get("motion") == (-8192, 8192)
        assert cfg.lock_map(), "no [tool.graftlint.locks] declarations"
        index = RepoIndex(cfg)
        roots = [
            qn for _rel, mod in index.modules.items()
            for qn in mod.read_path_funcs
        ]
        assert "snapshot_grid" in roots

    def test_jobs_parallel_parse_matches_serial(self, tmp_path):
        """--jobs N must be a pure speedup: identical findings."""
        (tmp_path / "pyproject.toml").write_text(GL011_CONFIG)
        (tmp_path / "pkg").mkdir()
        (tmp_path / "pkg" / "fx.py").write_text(textwrap.dedent("""
            def scale(d_q2, rate_q8):
                return d_q2 * rate_q8
        """))
        (tmp_path / "pkg" / "dev.py").write_text(textwrap.dedent(
            SEND_TEAR_SRC
        ))
        serial, _, _ = run_lint(str(tmp_path))
        parallel, _, _ = run_lint(str(tmp_path), jobs=2)
        assert [f.key() for f in serial] == [f.key() for f in parallel]
