"""graftlint self-tests: every rule fires on its positive fixture and
stays quiet on the negative twin, suppressions/markers behave, baseline
reconciliation is exact — and the REAL repo lints clean (the tier-1
gate that keeps the invariants enforced, not aspirational)."""

from __future__ import annotations

import json
import textwrap

import pytest

from rplidar_ros2_driver_tpu.tools.graftlint import load_config, run_lint

BASE_CONFIG = """
[tool.graftlint]
paths = ["pkg"]
static_params = ["cfg", "config", "self"]

[tool.graftlint.gl004]
zones = ["pkg/zone.py"]
int_returning = ["int_fn"]
int_names = ["counts_i"]
float_names = ["fx", "meta"]
bool_names = ["ok"]

[tool.graftlint.gl007]
files = ["pkg/hot.py"]

[tool.graftlint.gl008]
bench = "bench.py"
bench_meta_test = "tests/test_bench_meta.py"
params_module = "pkg/config.py"
params_yaml = "param.yaml"
unvalidated_params_ok = ["name"]
precompile_exempt = []
"""


def _lint(tmp_path, files: dict, config: str = BASE_CONFIG):
    (tmp_path / "pyproject.toml").write_text(config)
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    findings, new, stale = run_lint(str(tmp_path))
    return findings


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# GL001 — host syncs inside jit
# ---------------------------------------------------------------------------


class TestGL001:
    def test_fires_on_np_asarray_and_item_in_jit(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                y = np.asarray(x)
                return x + y.item()
        """})
        msgs = [f.message for f in fs if f.rule == "GL001"]
        assert any("np.asarray" in m for m in msgs)
        assert any(".item()" in m for m in msgs)

    def test_fires_on_float_of_traced_param(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """})
        assert any(
            f.rule == "GL001" and "float(x)" in f.message for f in fs
        )

    def test_quiet_on_host_function_and_scalar_params(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax
            import numpy as np

            def host_parse(res):
                return np.asarray(res)  # not jit-reachable

            @jax.jit
            def f(x, n: int):
                return x * int(n)
        """})
        assert "GL001" not in _rules(fs)

    def test_suppression_with_reason_works(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                # graftlint: disable=GL001 — fixture-sanctioned host sync
                return np.asarray(x)
        """})
        assert "GL001" not in _rules(fs)

    def test_suppression_without_reason_is_ignored(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                # graftlint: disable=GL001
                return np.asarray(x)
        """})
        assert "GL001" in _rules(fs)


# ---------------------------------------------------------------------------
# GL002 — Python branching on traced values
# ---------------------------------------------------------------------------


class TestGL002:
    def test_fires_on_if_over_traced_comparison(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """})
        assert "GL002" in _rules(fs)

    def test_quiet_on_static_config_shape_and_none_checks(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax

            @jax.jit
            def f(x, ms, cfg):
                if cfg.enable:
                    x = x * 2
                if x.shape[0] > 4:
                    x = x[:4]
                if ms is None:
                    return x
                return x + ms
        """})
        assert "GL002" not in _rules(fs)

    def test_scalar_annotation_is_trusted(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import jax

            @jax.jit
            def f(x, n: int):
                while n < 4:
                    n *= 2
                return x * n
        """})
        assert "GL002" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL003 — donation hygiene
# ---------------------------------------------------------------------------


class TestGL003:
    def test_fires_on_read_after_donation(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            def use(state, x):
                out = step(state, x)
                return out + state
        """})
        assert any(
            f.rule == "GL003" and "donated to step" in f.message for f in fs
        )

    def test_quiet_when_rebound(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            def use(state, x):
                state = step(state, x)
                state = step(state, x)
                return state
        """})
        assert "GL003" not in _rules(fs)

    def test_same_line_double_load_reports_not_crashes(self, tmp_path):
        # regression: two Loads of the donated name on ONE line used to
        # reach the AST nodes in the sort key (nodes don't compare) and
        # crash the whole run with TypeError
        fs = _lint(tmp_path, {"pkg/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            def use(state, x):
                out = step(state, x)
                return (state, state)
        """})
        assert any(
            f.rule == "GL003" and "donated to step" in f.message for f in fs
        )

    def test_fires_on_undonated_carry_entry_in_ops(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("cfg",))
            def step(state, x, cfg):
                return state + x
        """})
        assert any(
            f.rule == "GL003" and "without donate_argnums" in f.message
            for f in fs
        )

    def test_quiet_when_donated_or_justified(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/m.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            # graftlint: disable=GL003 — fixture-sanctioned debug API
            @jax.jit
            def debug_step(state, x):
                return state + x
        """})
        assert "GL003" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL004 — bit-exact zones
# ---------------------------------------------------------------------------


class TestGL004:
    def test_fires_on_float_reduction_and_unpoliced_cast(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/zone.py": """
            import jax.numpy as jnp

            def score(fx):
                total = jnp.sum(fx)
                return total.astype(jnp.int32)
        """})
        msgs = [f.message for f in fs if f.rule == "GL004"]
        assert any("reduction" in m for m in msgs)
        assert any("float→int cast" in m for m in msgs)

    def test_quiet_on_int_reduction_and_policed_cast(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/zone.py": """
            import jax.numpy as jnp

            def score(fx, ok):
                n = jnp.sum(ok.astype(jnp.int32))
                v = jnp.sum(int_fn(fx), axis=0)
                # graftlint: policed — fixture clamps fx upstream
                q = fx.astype(jnp.int32)
                return n + v + q

            def int_fn(fx):
                return (fx > 0).astype(jnp.int32)
        """})
        assert "GL004" not in _rules(fs)

    def test_zone_scoping(self, tmp_path):
        # identical float reduction OUTSIDE the declared zone: quiet
        fs = _lint(tmp_path, {"pkg/other.py": """
            import jax.numpy as jnp

            def score(fx):
                return jnp.sum(fx)
        """})
        assert "GL004" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL005 — weak-type promotion in zones
# ---------------------------------------------------------------------------


class TestGL005:
    def test_fires_on_bare_float_scalar(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/zone.py": """
            import jax.numpy as jnp

            def scale(fx):
                return fx * 0.5
        """})
        assert "GL005" in _rules(fs)

    def test_quiet_on_wrapped_scalar_and_int_literal(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/zone.py": """
            import jax.numpy as jnp

            def scale(fx):
                half = jnp.float32(0.5)
                return (fx * half + fx * jnp.float32(0.25)) * 2
        """})
        assert "GL005" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL006 — static_argnames hygiene
# ---------------------------------------------------------------------------


class TestGL006:
    def test_fires_on_mutable_static_value_and_unfrozen_config(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import dataclasses
            import functools
            import jax

            @dataclasses.dataclass
            class StepConfig:
                n: int = 4

            @functools.partial(jax.jit, static_argnames=("modes",))
            def f(x, modes):
                return x

            def call(x):
                return f(x, modes=[1, 2])
        """})
        msgs = [f.message for f in fs if f.rule == "GL006"]
        assert any("StepConfig" in m for m in msgs)
        assert any("mutable value" in m for m in msgs)

    def test_quiet_on_frozen_config_and_tuple(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import dataclasses
            import functools
            import jax

            @dataclasses.dataclass(frozen=True)
            class StepConfig:
                n: int = 4

            @functools.partial(jax.jit, static_argnames=("modes",))
            def f(x, modes):
                return x

            def call(x):
                return f(x, modes=(1, 2))
        """})
        assert "GL006" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL007 — hot-loop allocations
# ---------------------------------------------------------------------------


class TestGL007:
    def test_fires_inside_marked_region_only(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/hot.py": """
            import numpy as np

            # graftlint: hot-loop
            def dispatch(self, m):
                buf = np.zeros((m, 4), np.uint8)
                return buf

            def cold_setup(m):
                return np.zeros((m, 4), np.uint8)
        """})
        gl7 = [f for f in fs if f.rule == "GL007"]
        assert len(gl7) == 1 and "dispatch" not in gl7[0].message

    def test_def_marker_does_not_absorb_later_pairs_end(self, tmp_path):
        # regression: a def-scoped marker used to pair with ANY later
        # end-hot-loop, fusing everything between into one bogus region
        fs = _lint(tmp_path, {"pkg/hot.py": """
            import numpy as np

            # graftlint: hot-loop
            def dispatch(self, m):
                return m + 1

            def unrelated(m):
                return np.zeros((m,), np.uint8)  # NOT hot: must stay quiet

            def other(self, m, raw):
                # graftlint: hot-loop
                view = np.frombuffer(raw, np.uint8)
                # graftlint: end-hot-loop
                return view
        """})
        assert "GL007" not in _rules(fs)

    def test_region_markers_and_frombuffer_ok(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/hot.py": """
            import numpy as np

            def dispatch(self, m, raw):
                # graftlint: hot-loop
                view = np.frombuffer(raw, np.uint8)
                out = view.reshape(m, 4)
                # graftlint: end-hot-loop
                scratch = np.zeros((m,), np.uint8)
                return out, scratch
        """})
        assert "GL007" not in _rules(fs)


# ---------------------------------------------------------------------------
# GL008 — structural consistency
# ---------------------------------------------------------------------------


class TestGL008:
    def test_bench_pin_and_param_drift_fire(self, tmp_path):
        fs = _lint(tmp_path, {
            "bench.py": """
                GRADED = {1: ("chain", 100, {}), 2: ("e2e", 100, {})}
            """,
            "tests/test_bench_meta.py": """
                def test_names():
                    assert metric_name(1) == "one"
            """,
            "pkg/config.py": """
                import dataclasses

                @dataclasses.dataclass
                class DriverParams:
                    name: str = "x"
                    rate: int = 7
                    ghost: int = 1

                    def validate(self):
                        if self.rate < 0:
                            raise ValueError("rate")
            """,
            "param.yaml": """
                name: x
                rate: 7
                stale_key: true
            """,
        })
        msgs = [f.message for f in fs if f.rule == "GL008"]
        assert any("metric_name(2)" in m for m in msgs)
        assert any("DriverParams.ghost" in m for m in msgs)  # not in yaml
        assert any("never validated" in m and "ghost" in m for m in msgs)
        assert any("stale_key" in m for m in msgs)

    def test_precompile_reachability(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def warmed(state, x):
                return state + x

            @functools.partial(jax.jit, donate_argnums=(0,))
            def cold(state, x):
                return state - x

            def precompile():
                warmed(0, 1)
        """})
        gl8 = [f.message for f in fs if f.rule == "GL008"]
        assert any("cold" in m for m in gl8)
        assert not any("warmed" in m for m in gl8)


# ---------------------------------------------------------------------------
# GL009 — unbounded retry loops
# ---------------------------------------------------------------------------


class TestGL009:
    def test_fires_on_constant_sleep_retry_loop(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def reconnect(dev):
                while True:
                    if dev.connect():
                        break
                    time.sleep(1.0)
        """})
        msgs = [f.message for f in fs if f.rule == "GL009"]
        assert len(msgs) == 1 and "unbounded retry loop" in msgs[0]

    def test_quiet_on_computed_backoff(self, tmp_path):
        # non-constant sleep argument = a computed backoff: absolved
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def reconnect(dev, policy):
                while True:
                    if dev.connect():
                        break
                    time.sleep(policy.next_delay())
        """})
        assert "GL009" not in _rules(fs)

    def test_quiet_on_attempt_cap_and_deadline(self, tmp_path):
        # comparison-gated escapes (attempt cap, deadline) are the bound
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def capped(dev):
                attempt = 0
                while True:
                    if dev.connect():
                        break
                    attempt += 1
                    if attempt >= 5:
                        raise RuntimeError("gave up")
                    time.sleep(1.0)

            def deadlined(dev, deadline):
                while True:
                    if dev.connect():
                        return True
                    if time.monotonic() > deadline:
                        return False
                    time.sleep(1.0)
        """})
        assert "GL009" not in _rules(fs)

    def test_quiet_on_bounded_while_condition(self, tmp_path):
        # not `while True`: the loop condition itself is the bound
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def run(self):
                while self._running.is_set():
                    self.poll()
                    time.sleep(0.2)
        """})
        assert "GL009" not in _rules(fs)

    def test_suppression_with_reason_works(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            def watchdog(dev):
                # graftlint: disable=GL009 — fixture-sanctioned daemon poll
                while True:
                    dev.kick()
                    time.sleep(5.0)
        """})
        assert "GL009" not in _rules(fs)

    def test_closure_in_method_reports_once(self, tmp_path):
        # regression: the nested-def skip used split('.')[0], so a
        # retry loop in a closure inside a METHOD was reported twice
        # (once per qualname walk) — unbaselineable, since the two
        # messages differ
        fs = _lint(tmp_path, {"pkg/m.py": """
            import time

            class Node:
                def start(self, dev):
                    def worker():
                        while True:
                            if dev.connect():
                                break
                            time.sleep(1.0)
                    return worker
        """})
        gl9 = [f for f in fs if f.rule == "GL009"]
        assert len(gl9) == 1, [f.message for f in gl9]

    def test_baseline_reconcile_covers_gl009(self, tmp_path):
        """A baselined GL009 finding passes; a stale GL009 entry fails
        (the same exact-description contract every rule carries)."""
        src = {"pkg/m.py": """
            import time

            def reconnect(dev):
                while True:
                    if dev.connect():
                        break
                    time.sleep(1.0)
        """}
        (tmp_path / "pyproject.toml").write_text(BASE_CONFIG)
        for rel, body in src.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(body))
        findings, new, stale = run_lint(str(tmp_path))
        target = [f for f in findings if f.rule == "GL009"][0]
        (tmp_path / "graftlint.baseline.json").write_text(json.dumps({
            "findings": [{
                "rule": target.rule, "path": target.path,
                "message": target.message,
                "justification": "fixture: legacy loop, fix queued",
            }, {
                "rule": "GL009", "path": "pkg/gone.py",
                "message": "no longer fires",
                "justification": "stale entry",
            }]
        }))
        findings, new, stale = run_lint(str(tmp_path))
        assert not any(f.key() == target.key() for f in new)
        assert len(stale) == 1 and stale[0]["path"] == "pkg/gone.py"


# ---------------------------------------------------------------------------
# GL010 — pallas_call must ride the compiled-vs-interpret selector
# ---------------------------------------------------------------------------


_GL010_GOOD = """
    import functools
    import jax
    from jax.experimental import pallas as pl

    def _lowering_dispatch(compiled_fn, interpret_fn, *args):
        return jax.lax.platform_dependent(
            *args, tpu=compiled_fn, default=interpret_fn
        )

    def _kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:]

    def _call(x, interpret):
        return pl.pallas_call(_kernel, interpret=interpret)(x)

    def entry(x, interpret=None):
        if interpret is None:
            return _lowering_dispatch(
                functools.partial(_call, interpret=False),
                functools.partial(_call, interpret=True),
                x,
            )
        return _call(x, interpret)
"""


class TestGL010:
    def test_fires_on_missing_interpret_kwarg(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                return pl.pallas_call(_kernel)(x)
        """})
        msgs = [f.message for f in fs if f.rule == "GL010"]
        assert len(msgs) == 1 and "no `interpret=`" in msgs[0]

    def test_fires_on_constant_interpret(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                return pl.pallas_call(_kernel, interpret=False)(x)
        """})
        msgs = [f.message for f in fs if f.rule == "GL010"]
        assert len(msgs) == 1 and "constant" in msgs[0]

    def test_fires_on_computed_interpret(self, tmp_path):
        """The lowering choice computed in place (process default
        backend — the exact bug _lowering_dispatch exists to prevent)
        is no better than a constant."""
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            import jax
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                interp = jax.default_backend() != "tpu"
                return pl.pallas_call(_kernel, interpret=interp)(x)
        """})
        msgs = [f.message for f in fs if f.rule == "GL010"]
        assert len(msgs) == 1 and "not a parameter" in msgs[0]

    def test_fires_without_module_selector(self, tmp_path):
        """interpret threaded as a parameter but no _lowering_dispatch
        anywhere in the module: nothing sanctioned ever supplies it."""
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x, interpret):
                return pl.pallas_call(_kernel, interpret=interpret)(x)
        """})
        msgs = [f.message for f in fs if f.rule == "GL010"]
        assert len(msgs) == 1 and "_lowering_dispatch" in msgs[0]

    def test_quiet_on_the_sanctioned_pattern(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": _GL010_GOOD})
        assert "GL010" not in _rules(fs)

    def test_quiet_on_imported_selector(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from pkg.ops.base import _lowering_dispatch
            import functools
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def _call(x, interpret):
                return pl.pallas_call(_kernel, interpret=interpret)(x)

            def entry(x):
                return _lowering_dispatch(
                    functools.partial(_call, interpret=False),
                    functools.partial(_call, interpret=True),
                    x,
                )
        """, "pkg/ops/base.py": """
            import jax

            def _lowering_dispatch(compiled_fn, interpret_fn, *args):
                return jax.lax.platform_dependent(
                    *args, tpu=compiled_fn, default=interpret_fn
                )
        """})
        assert "GL010" not in _rules(fs)

    def test_quiet_outside_ops(self, tmp_path):
        """The rule polices ops/ — a bench-local experiment kernel is
        not a production lowering."""
        fs = _lint(tmp_path, {"pkg/scratch.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                return pl.pallas_call(_kernel, interpret=False)(x)
        """})
        assert "GL010" not in _rules(fs)

    def test_suppression_with_reason_works(self, tmp_path):
        fs = _lint(tmp_path, {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                # graftlint: disable=GL010 — fixture-sanctioned TPU-only tool
                return pl.pallas_call(_kernel, interpret=False)(x)
        """})
        assert "GL010" not in _rules(fs)

    def test_baseline_reconcile_covers_gl010(self, tmp_path):
        src = {"pkg/ops/k.py": """
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]

            def call(x):
                return pl.pallas_call(_kernel)(x)
        """}
        (tmp_path / "pyproject.toml").write_text(BASE_CONFIG)
        for rel, body in src.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(body))
        findings, new, stale = run_lint(str(tmp_path))
        target = [f for f in findings if f.rule == "GL010"][0]
        (tmp_path / "graftlint.baseline.json").write_text(json.dumps({
            "findings": [{
                "rule": target.rule, "path": target.path,
                "message": target.message,
                "justification": "fixture: port to selector queued",
            }, {
                "rule": "GL010", "path": "pkg/ops/gone.py",
                "message": "no longer fires",
                "justification": "stale entry",
            }]
        }))
        findings, new, stale = run_lint(str(tmp_path))
        assert not any(f.key() == target.key() for f in new)
        assert len(stale) == 1 and stale[0]["path"] == "pkg/ops/gone.py"


# ---------------------------------------------------------------------------
# baseline reconciliation
# ---------------------------------------------------------------------------


class TestBaseline:
    def test_baselined_finding_passes_and_stale_fails(self, tmp_path):
        files = {"pkg/zone.py": """
            import jax.numpy as jnp

            def scale(fx):
                return fx * 0.5
        """}
        (tmp_path / "pyproject.toml").write_text(BASE_CONFIG)
        for rel, src in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        findings, new, stale = run_lint(str(tmp_path))
        target = [f for f in findings if f.rule == "GL005"][0]
        baseline = {
            "findings": [{
                "rule": target.rule, "path": target.path,
                "message": target.message,
                "justification": "fixture: known weak-type site",
            }, {
                "rule": "GL001", "path": "pkg/zone.py",
                "message": "no longer fires",
                "justification": "stale entry",
            }]
        }
        (tmp_path / "graftlint.baseline.json").write_text(
            json.dumps(baseline)
        )
        findings, new, stale = run_lint(str(tmp_path))
        assert not any(f.key() == target.key() for f in new)
        assert len(stale) == 1 and stale[0]["message"] == "no longer fires"

    def test_baseline_entry_requires_justification(self, tmp_path):
        (tmp_path / "pyproject.toml").write_text(BASE_CONFIG)
        (tmp_path / "pkg").mkdir()
        (tmp_path / "pkg" / "m.py").write_text("x = 1\n")
        (tmp_path / "graftlint.baseline.json").write_text(json.dumps({
            "findings": [{"rule": "GL001", "path": "a", "message": "b"}]
        }))
        with pytest.raises(ValueError, match="justification"):
            run_lint(str(tmp_path))


# ---------------------------------------------------------------------------
# the repo itself
# ---------------------------------------------------------------------------


class TestRepoClean:
    def test_repo_lints_clean_with_all_rules_active(self):
        """The acceptance gate: the real tree has no unbaselined finding
        and no stale baseline entry, with every rule loaded."""
        from rplidar_ros2_driver_tpu.tools.graftlint.rules import ALL_RULES
        from rplidar_ros2_driver_tpu.tools.graftlint.runner import repo_root

        assert len(ALL_RULES) >= 10
        findings, new, stale = run_lint(repo_root())
        assert new == [], [f"{f.path}:{f.line} {f.rule} {f.message}"
                           for f in new]
        assert stale == []

    def test_repo_config_declares_zones_and_hot_files(self):
        from rplidar_ros2_driver_tpu.tools.graftlint.runner import repo_root

        cfg = load_config(repo_root())
        assert any("ops/ingest.py" in z for z in cfg.zones)
        assert any("ops/scan_match" in z for z in cfg.zones)
        assert any("driver/ingest.py" in h for h in cfg.hot_files)
