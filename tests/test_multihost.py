"""Multi-host bring-up (parallel/multihost.py) — single-process paths.

True multi-process needs N coordinated interpreters; what CAN be pinned
down hermetically: topology detection, the no-op single-process path,
global-mesh construction over the virtual device set, and the
stream-ownership arithmetic every process uses to pick its lidars.
"""

import os
from unittest import mock

import jax
import pytest

from rplidar_ros2_driver_tpu.parallel import multihost


def test_not_configured_without_env():
    with mock.patch.dict(os.environ, {}, clear=False):
        os.environ.pop("JAX_COORDINATOR_ADDRESS", None)
        assert not multihost.is_configured()
        assert multihost.initialize() is False  # single-process: no-op


def test_configured_detection():
    with mock.patch.dict(
        os.environ, {"JAX_COORDINATOR_ADDRESS": "10.0.0.1:1234"}
    ):
        assert multihost.is_configured()


def test_initialize_passes_topology_through():
    """The env topology must reach jax.distributed.initialize verbatim."""
    try:
        with mock.patch.dict(
            os.environ,
            {
                "JAX_COORDINATOR_ADDRESS": "10.0.0.1:1234",
                "JAX_NUM_PROCESSES": "4",
                "JAX_PROCESS_ID": "2",
            },
        ), mock.patch.object(jax.distributed, "initialize") as init:
            assert multihost.initialize() is True
            init.assert_called_once_with(
                coordinator_address="10.0.0.1:1234", num_processes=4, process_id=2
            )
    finally:
        multihost._initialized = False  # undo the module latch regardless


def test_partial_topology_is_an_error():
    """A coordinator address without process count/id must fail loudly,
    not default every host to its own 1-process job."""
    env = {"JAX_COORDINATOR_ADDRESS": "10.0.0.1:1234"}
    with mock.patch.dict(os.environ, env):
        os.environ.pop("JAX_NUM_PROCESSES", None)
        os.environ.pop("JAX_PROCESS_ID", None)
        with pytest.raises(ValueError, match="JAX_NUM_PROCESSES"):
            multihost.initialize()
    with mock.patch.dict(
        os.environ, {**env, "JAX_NUM_PROCESSES": "4"}
    ):
        os.environ.pop("JAX_PROCESS_ID", None)
        with pytest.raises(ValueError, match="JAX_PROCESS_ID"):
            multihost.initialize()


def test_global_mesh_single_process():
    """Single process: the global mesh is just the local (stream, beam)
    mesh over every visible device (8 virtual CPUs under conftest)."""
    mesh = multihost.make_global_mesh()
    assert set(mesh.axis_names) == {"stream", "beam"}
    assert mesh.devices.size == len(jax.devices())


def test_local_stream_slice_single_process():
    assert multihost.local_stream_slice(6) == slice(0, 6)


def test_local_stream_slice_multi_process_arithmetic():
    with mock.patch.object(jax, "process_index", return_value=1), mock.patch.object(
        jax, "process_count", return_value=4
    ):
        assert multihost.local_stream_slice(8) == slice(2, 4)
        with pytest.raises(ValueError):
            multihost.local_stream_slice(6)  # 6 streams / 4 processes
