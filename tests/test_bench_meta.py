"""bench.py metadata invariants (no device work — safe on CPU CI).

The driver keys benchmark series by metric name; success and failure
records of one config must share a name, and no two configs may collide.
"""

import bench


def test_metric_names_unique_across_configs():
    names = {c: bench.metric_name(c) for c in bench.GRADED}
    assert len(set(names.values())) == len(names), names


def test_metric_names_stable():
    # the driver's recorded series — renames would orphan history
    assert bench.metric_name(5) == "denseboost64_filter_chain_scans_per_sec"
    assert bench.metric_name(6) == "e2e_decode_chain_scans_per_sec"
    assert bench.metric_name(1) == "a1m8_passthrough_scans_per_sec"
    assert bench.metric_name(2) == "graded_config2_scans_per_sec"
    assert bench.metric_name(3) == "graded_config3_scans_per_sec"
    assert bench.metric_name(7) == "fused_replay_scans_per_sec"
    assert bench.metric_name(4) == "graded_config4_scans_per_sec"
    assert bench.metric_name(9) == "fused_ingest_bytes_to_output_scans_per_sec"
    assert bench.metric_name(8) == "fleet_fused_replay_scans_per_sec"
    assert bench.metric_name(10) == "fleet_fused_ingest_bytes_to_scans_per_sec"
    assert bench.metric_name(11) == "super_tick_drain_scans_per_sec"
    assert bench.metric_name(12) == "mapping_match_update_scans_per_sec"
    assert bench.metric_name(13) == "chaos_degraded_fleet_scans_per_sec"
    assert bench.metric_name(14) == "pallas_match_kernel_scans_per_sec"
    assert bench.metric_name(15) == "shard_failover_survivor_scans_per_sec"
    assert bench.metric_name(16) == "deskew_recon_map_updates_per_sec"
    assert bench.metric_name(17) == "loop_close_corrected_scans_per_sec"
    assert bench.metric_name(18) == "fused_mapping_stack_updates_per_sec"
    assert bench.metric_name(19) == "elastic_serving_adaptive_scans_per_sec"
    assert bench.metric_name(20) == "async_serving_overlapped_scans_per_sec"
    assert bench.metric_name(21) == "pod_scaleout_balanced_scans_per_sec"
    assert bench.metric_name(22) == "map_serving_tile_reads_per_sec"
    assert bench.metric_name(23) == "scenario_matrix_scans_per_sec"


def test_graded_table_well_formed():
    for c, (kind, points, over) in bench.GRADED.items():
        assert kind in (
            "passthrough", "chain", "e2e", "fused", "fleet", "ingest",
            "fleet_ingest", "super_tick", "mapping", "chaos",
            "pallas_match", "failover", "deskew", "loop_close",
            "fused_mapping", "elastic_serving", "async_serving",
            "pod_scaleout", "map_serving", "scenarios",
        )
        assert points > 0
        assert isinstance(over, dict)


def test_probe_retry_returns_first_success():
    from rplidar_ros2_driver_tpu.utils.backend import (
        probe_jax_backend_with_retry,
    )

    calls = []

    def flaky(timeout_s):
        calls.append(timeout_s)
        return (len(calls) >= 3), ("ok" if len(calls) >= 3 else "down")

    ok, detail = probe_jax_backend_with_retry(
        total_budget_s=60.0, per_probe_s=5.0, interval_s=0.01, _probe=flaky
    )
    assert ok and detail == "ok"
    assert len(calls) == 3


def test_probe_retry_exhausts_budget_with_last_error():
    from rplidar_ros2_driver_tpu.utils.backend import (
        probe_jax_backend_with_retry,
    )

    logs = []
    ok, detail = probe_jax_backend_with_retry(
        total_budget_s=0.05, per_probe_s=5.0, interval_s=0.02,
        log=logs.append, _probe=lambda t: (False, "tunnel dead"),
    )
    assert not ok
    assert "tunnel dead" in detail and "probes" in detail
    assert logs  # progress was reported


def test_guarded_backend_init_env_and_poisoned_flag(monkeypatch):
    """The shared two-stage guard must honor the BENCH_PROBE_* env knobs
    and report poisoned=True only when the subprocess probe succeeded
    but this process's init then hung."""
    from rplidar_ros2_driver_tpu.utils import backend as B

    monkeypatch.setenv("BENCH_PROBE_BUDGET_S", "0.05")
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT_S", "5")
    monkeypatch.setenv("BENCH_PROBE_INTERVAL_S", "0.02")

    # stage 1 exhausts: not ok, NOT poisoned (in-process init never ran)
    monkeypatch.setattr(
        B, "probe_jax_backend_subprocess", lambda t: (False, "down")
    )
    ok, detail, poisoned = B.guarded_backend_init()
    assert not ok and not poisoned and "down" in detail

    # stage 1 passes, stage 2 (in-process) hangs: poisoned
    monkeypatch.setattr(
        B, "probe_jax_backend_subprocess", lambda t: (True, "up")
    )
    monkeypatch.setattr(B, "probe_jax_backend", lambda t: (False, "hung"))
    ok, detail, poisoned = B.guarded_backend_init()
    assert not ok and poisoned and detail == "hung"

    # both pass
    monkeypatch.setattr(B, "probe_jax_backend", lambda t: (True, "dev0"))
    ok, detail, poisoned = B.guarded_backend_init()
    assert ok and not poisoned and detail == "dev0"


def test_probe_nonpositive_timeout_reports_misconfig():
    """A zero/negative probe timeout (one typo away in
    BENCH_PROBE_TIMEOUT_S) must produce a configuration diagnostic, not
    a ValueError from the deadline helper masquerading as the probe
    failure (r4 ADVICE)."""
    from rplidar_ros2_driver_tpu.utils.backend import (
        probe_jax_backend,
        probe_jax_backend_subprocess,
    )

    for fn in (probe_jax_backend, probe_jax_backend_subprocess):
        for bad in (0, -1, 0.0):
            ok, detail = fn(bad)
            assert not ok
            assert "BENCH_PROBE_TIMEOUT_S" in detail, detail
            assert "ValueError" not in detail


def test_record_last_good_is_link_aware(monkeypatch, tmp_path):
    """The sidecar must never let a decisively-sicker-link streaming run
    overwrite a healthier entry with a lower number (r4 VERDICT #5: the
    degraded-link 7.4 scans/s e2e must not stand as capability) — while
    better numbers, healthier links, and the link-independent
    device-resident class overwrite normally."""
    metric = bench.metric_name(6)
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(tmp_path / "lg.json"))

    def rec(value, rtt, **over):
        bench._record_last_good({
            "metric": metric, "value": value, "unit": "scans/s",
            "device": "tpu", "barrier_rtt_ms": rtt, **over,
        })
        return bench._load_last_good()[metric]

    e = rec(30.0, 1.0)
    assert e["value"] == 30.0 and e["barrier_rtt_ms"] == 1.0

    # sicker link (>2.5x RTT) + lower value: refused, recorded beside
    e = rec(7.4, 40.0)
    assert e["value"] == 30.0
    assert e["degraded_link_run"]["value"] == 7.4
    assert e["degraded_link_run"]["barrier_rtt_ms"] == 40.0

    # sicker link but a BETTER value: overwrites (not link-caused)
    e = rec(50.0, 40.0)
    assert e["value"] == 50.0 and "degraded_link_run" not in e

    # healthier link, lower value: overwrites (a real regression must
    # not be hidden behind the link heuristic)
    e = rec(20.0, 1.0)
    assert e["value"] == 20.0

    # link weather within the healthy ~2x drift: overwrites
    e = rec(18.0, 1.9)
    assert e["value"] == 18.0

    # the device-resident class is link-independent: always overwrites,
    # and config 5's median_ab RTT rides into the entry
    m5 = bench.metric_name(5)
    bench._record_last_good({
        "metric": m5, "value": 33000.0, "unit": "scans/s", "device": "tpu",
        "measurement": "device_resident_in_jit",
        "median_ab": {"barrier_rtt_ms": 1.0}, "link_put_ms": 2.0,
    })
    bench._record_last_good({
        "metric": m5, "value": 32000.0, "unit": "scans/s", "device": "tpu",
        "measurement": "device_resident_in_jit",
        "median_ab": {"barrier_rtt_ms": 200.0}, "link_put_ms": 9.0,
    })
    e = bench._load_last_good()[m5]
    assert e["value"] == 32000.0
    assert e["barrier_rtt_ms"] == 200.0 and e["link_put_ms"] == 9.0


def test_step_ablation_smoke():
    """The ablation tool must keep running against the real counted step
    (tiny shapes — this pins the harness, not the numbers)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "scripts/step_ablation.py", "--cpu",
         "--iters", "10", "--rounds", "1", "--window", "4"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert set(out["ablation_us"]) == {
        "full_scatter", "full_dense", "full_voxel_matmul",
        "full_median_xla", "full_median_inc",
        "full_median_inc_pallas", "full_median_inc_xla",
        "no_median", "no_voxel", "no_clip", "resample_only",
    }
    assert all(v > 0 for v in out["ablation_us"].values())
    assert out["device"] == "cpu"
    # the lowering-A/B decision key must ride in derived whenever both
    # pinned inc cases measured
    assert "inc_pallas_vs_inc_xla_speedup" in out["derived"]


def test_decide_backends_analyze():
    """The standing decision procedure as code: TPU records move the
    recommendations past the 5% bar, CPU records never do, and the
    window-aware crossover surfaces as a threshold."""
    import importlib
    import os
    import sys

    sys.modules.pop("decide_backends", None)
    scripts_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    )
    sys.path.insert(0, scripts_dir)
    try:
        db = importlib.import_module("decide_backends")
    finally:
        # remove by value: imports can insert their own sys.path entries,
        # so pop(0) could evict the wrong one and leak scripts/ forever
        sys.path.remove(scripts_dir)

    records = [
        {  # config 5 on TPU: inc_pallas decisively beats the headline
            "metric": "denseboost64_filter_chain_scans_per_sec",
            "device": "tpu",
            "median_ab": {
                "speedup": 2.1,
                "inc_vs_headline_speedup": 0.3,
                "inc_pallas_vs_headline_speedup": 1.4,
                "inc_pallas_vs_inc_xla_speedup": 4.6,
                "barrier_rtt_ms": 1.2,
            },
        },
        {  # deep windows: crossover at 512
            "device": "tpu",
            "deep_window_ab": {
                "256": {"inc_vs_best_sort_speedup": 0.95},
                "512": {"inc_vs_best_sort_speedup": 1.31},
            },
        },
        {  # ablation: voxel matmul wins, dense resample is a tie
            "device": "tpu",
            "derived": {
                "matmul_vs_scatter_voxel_speedup": 1.22,
                "dense_vs_scatter_speedup": 1.001,
            },
        },
        {  # a CPU fallback must carry NO decision weight
            "device": "cpu",
            "derived": {"matmul_vs_scatter_voxel_speedup": 0.8},
            "median_ab": {"inc_pallas_vs_headline_speedup": 9.0},
        },
        {  # a device-less record must be visibly reported, not dropped
            "derived": {"matmul_vs_scatter_voxel_speedup": 7.0},
        },
    ]
    out = db.analyze(records)
    recs = out["recommendations"]
    assert recs["median_backend.tpu"]["flip"] is True
    assert recs["median_backend.tpu"]["recommended"] == "inc"
    assert recs["median_backend.tpu"]["value"] == 1.4  # not the cpu 9.0
    thr = recs["median_backend.tpu.window_threshold"]
    assert "window >= 512" in thr["recommended"]
    assert recs["voxel_backend.tpu"]["flip"] is True
    assert recs["voxel_backend.tpu"]["recommended"] == "matmul"
    assert recs["voxel_backend.tpu"]["value"] == 1.22  # not cpu 0.8/None 7.0
    assert recs["resample_backend.tpu"]["flip"] is False
    assert recs["resample_backend.tpu"]["recommended"] == "scatter"
    assert len(out["non_tpu_ignored"]) == 2  # cpu + device-less, once each

    # the threshold must be an upward-closed suffix: one just-over-bar
    # shallow window with deeper windows below the bar flips nothing
    noisy = db.analyze([{
        "device": "tpu",
        "deep_window_ab": {
            "256": {"inc_vs_best_sort_speedup": 1.06},
            "512": {"inc_vs_best_sort_speedup": 0.92},
            "1024": {"inc_vs_best_sort_speedup": 1.2},
        },
    }])
    thr = noisy["recommendations"]["median_backend.tpu.window_threshold"]
    assert "window >= 1024" in thr["recommended"]

    # strongest-evidence merge is symmetric in log space: a 1.30x
    # slowdown outweighs a later 1.25x win for the same mapping
    merged = db.analyze([
        {"device": "tpu", "derived": {"matmul_vs_scatter_voxel_speedup": 0.77}},
        {"device": "tpu", "derived": {"matmul_vs_scatter_voxel_speedup": 1.25}},
    ])
    assert merged["recommendations"]["voxel_backend.tpu"]["value"] == 0.77
    assert merged["recommendations"]["voxel_backend.tpu"]["flip"] is False


def test_fleet_latency_smoke():
    """The live fleet-tick tool (N sim devices -> real drivers -> one
    sharded pipelined tick per revolution period) must keep running end
    to end and emit a well-formed artifact — tiny pace/shapes; this pins
    the harness, not the numbers."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "scripts/fleet_latency.py", "--cpu",
         "--streams", "2", "--seconds", "3", "--rate-mult", "0.3",
         "--window", "4"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "fleet_live_pipelined_tick"
    assert out["streams"] == 2 and out["ticks"] > 0
    # keep_up (vs NOMINAL device pace) is recorded but not bounded here:
    # on a throttled CI host the sim pacing threads get starved then
    # released, bursting above nominal pace — load weather, not the
    # harness.  keep_up_vs_input is the structural invariant (outputs
    # can never exceed submitted revolutions).
    assert out["value"] > 0 and out["keep_up"] > 0
    assert 0 < out["keep_up_vs_input"] <= 1.0
    assert out["measured_span_s"] >= out["nominal_seconds"] > 0
    assert out["tick_p99_ms"] > 0
    assert out["staleness_ticks"] == 1
    assert out["device"] == "cpu"


def test_fleet_latency_emits_error_artifact_on_wedge():
    """Same wedge contract as the sibling tools: a blocked device
    round-trip must emit a structured error artifact and exit 0 — never
    hang the recapture queue (the tool runs LAST in one scarce rig
    window)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BENCH_RUN_DEADLINE_S="0.001")
    r = subprocess.run(
        [sys.executable, "scripts/fleet_latency.py", "--cpu",
         "--streams", "2", "--seconds", "2", "--rate-mult", "0.3",
         "--window", "4"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "fleet_live_pipelined_tick"
    assert "wedged" in out["error"].lower()
    assert "ticks_completed" in out


def test_bench_outage_artifact_is_structured_not_zero():
    """With the probe forced to fail, bench must still emit a nonzero
    CPU-computed artifact flagged device_unavailable, carrying the last
    good on-device headline + its date (r3 VERDICT #1; the headline
    entry comes from the committed LAST_GOOD_DEVICE.json sidecar) — and
    exit 0."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BENCH_FORCE_PROBE_FAIL="1")
    r = subprocess.run(
        [sys.executable, "bench.py", "--config", "3"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["device_unavailable"] is True
    assert out["value"] > 0.0, out
    assert out["device"] == "cpu"
    assert "forced by BENCH_FORCE_PROBE_FAIL" in out["probe_error"]
    assert out["metric"] == bench.metric_name(3)
    # the seeded sidecar's headline entry rides along with its date
    assert out["last_good_headline"]["value"] > 0
    assert out["last_good_headline"]["date"]


def test_config5_four_arm_branch_executes(monkeypatch):
    """The device branch of config 5 (four median arms — the inc arm is
    PINNED per lowering so the continuity key keeps its r2..r4 meaning —
    with RTT-adaptive rounds) must execute end to end: a crash here
    would zero the driver's end-of-round artifact.  Runners and the
    platform check are stubbed so the branch's own logic runs
    host-side."""
    import bench

    class FakeRunner:
        rates = {
            "pallas": 30000.0,
            "xla": 15000.0,
            "inc_xla": 45000.0,
            "inc_pallas": 60000.0,
        }

        def __init__(self, cfg, points):
            self.cfg = cfg
            self._rate = self.rates[cfg.median_backend]

        def measure_barrier_rtt_ms(self):
            return 1.0

        def measure_device_only(self, iters):
            return self._rate

        def measure_round(self, iters):
            return 300.0

        def measure_sync_p99(self):
            return 5.0

        def measure_link_put_ms(self):
            return 1.0

    class FakeDev:
        platform = "tpu"

    monkeypatch.setattr(bench, "_ChainRunner", FakeRunner)
    monkeypatch.setattr(bench.jax, "devices", lambda: [FakeDev()])
    out = bench.main(5, "pallas")
    ab = out["median_ab"]
    arms = {"pallas", "xla", "inc_xla", "inc_pallas"}
    assert out["value"] == 30000.0  # headline stays the selected backend
    assert arms <= set(ab)
    assert ab["speedup"] == 2.0                    # pallas/xla continuity key
    # continuity key still means "jnp inc formulation vs headline"
    assert ab["inc_vs_headline_speedup"] == 1.5
    # the lowering A/B that decides the TPU auto mapping
    assert ab["inc_pallas_vs_headline_speedup"] == 2.0
    assert ab["inc_pallas_vs_inc_xla_speedup"] == round(60000.0 / 45000.0, 3)
    assert set(ab["rounds"]) == arms
    assert "barrier_rtt_ms" in ab and set(ab["round_iters"]) == set(ab["rounds"])


def test_rtt_adaptive_iters_scenarios():
    """The round-sizing helper across the regimes that have actually
    bitten: local chip, sick link, quiet-probe RTT draw, fast kernel on
    a sick link, pathologically slow arm."""

    def mk(step_s, rtt_s):
        return lambda it: it / (it * step_s + rtt_s)

    # local chip (sub-ms RTT): keep the short base rounds
    assert bench._rtt_adaptive_iters(mk(30e-6, 0.05e-3), 0.05, 3000) == 3000
    # 78 ms RTT, 30 us step: the r4 recapture regime (~52k iters)
    n = bench._rtt_adaptive_iters(mk(30e-6, 78e-3), 78.0, 3000)
    assert 40_000 < n < 70_000
    # quiet-probe draw (probe rtt 100 ms vs median 200): difference
    # estimator recovers the true step; rounds stay minutes-free
    seq = [100e-3] * 3

    def quiet(it):
        return it / (it * 30e-6 + seq.pop(0))

    n = bench._rtt_adaptive_iters(quiet, 200.0, 3000)
    assert n * 30e-6 < 16
    # fast kernel (3 us) on a 200 ms link: barrier held near 5%
    n = bench._rtt_adaptive_iters(mk(3e-6, 200e-3), 200.0, 3000)
    frac = 200e-3 / (n * 3e-6 + 200e-3)
    assert frac < 0.06
    # pathologically slow arm (100 ms/step): micro probe bounds every
    # round to the wall cap instead of a 5-minute probe
    calls = []

    def slow(it):
        calls.append(it)
        return it / (it * 0.1 + 78e-3)

    n = bench._rtt_adaptive_iters(slow, 78.0, 3000)
    assert n * 0.1 <= 16
    assert max(calls) < 3000  # never ran the full-length probe


def test_run_with_deadline_semantics():
    """Value passthrough, exception passthrough, and the wedge timeout
    (a blocked device fetch sits in native code where no signal can
    reach it — the daemon-thread deadline is the only way out)."""
    import time

    from rplidar_ros2_driver_tpu.utils.backend import (
        MeasurementWedgedError,
        run_with_deadline,
    )

    assert run_with_deadline(lambda: 42, 5.0) == 42

    class Boom(RuntimeError):
        pass

    def raises():
        raise Boom("real failure")

    try:
        run_with_deadline(raises, 5.0)
        raise AssertionError("exception should propagate")
    except Boom:
        pass

    t0 = time.monotonic()
    try:
        run_with_deadline(lambda: time.sleep(30), 0.2, what="fake fetch")
        raise AssertionError("timeout should raise")
    except MeasurementWedgedError as e:
        assert "fake fetch" in str(e)
    assert time.monotonic() - t0 < 5.0


def test_deep_window_ab_emits_partial_artifact_on_wedge():
    """A wedged window must not cost the windows already measured, and
    later windows are skipped (the process's backend is hostage to the
    blocked fetch) — the artifact still lands with exit 0.  Forced via
    a sub-measurement deadline."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BENCH_WINDOW_DEADLINE_S="0.001")
    r = subprocess.run(
        [sys.executable, "scripts/deep_window_ab.py", "--cpu",
         "--windows", "4", "8", "--backends", "xla",
         "--iters", "5", "--rounds", "1"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    ab = out["deep_window_ab"]
    assert "wedged" in ab["4"]["error"].lower() or "Wedged" in ab["4"]["error"]
    assert ab["8"]["skipped"] == "link wedged during W=4"


def test_step_ablation_emits_partial_artifact_on_wedge():
    """Same contract for the ablation tool: a wedge mid-sequence emits
    the cases measured so far plus an error key, exit 0, and derived
    ratios are omitted (never fabricated) when their inputs are
    missing."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BENCH_CASE_DEADLINE_S="0.001")
    r = subprocess.run(
        [sys.executable, "scripts/step_ablation.py", "--cpu",
         "--iters", "5", "--rounds", "1", "--window", "4"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert "wedged" in out["error"].lower()
    assert out["derived"] == {}


def test_run_with_deadline_nested_timeout_not_mistaken_for_wedge():
    """A DeadlineExpired raised by fn ITSELF (e.g. a nested
    bounded_fetch / chain collect timeout) must propagate as-is — only
    the outer wait's expiry converts to MeasurementWedgedError — and a
    falsy deadline is rejected rather than silently unbounded."""
    import pytest

    from rplidar_ros2_driver_tpu.utils.backend import (
        MeasurementWedgedError,
        run_with_deadline,
    )
    from rplidar_ros2_driver_tpu.utils.fetch import DeadlineExpired

    def inner_timeout():
        raise DeadlineExpired("publish collect (device->host) exceeded 5 s")

    with pytest.raises(DeadlineExpired):
        run_with_deadline(inner_timeout, 10.0)
    try:
        run_with_deadline(inner_timeout, 10.0)
    except MeasurementWedgedError:
        raise AssertionError("nested timeout misreported as wedge")
    except DeadlineExpired:
        pass

    with pytest.raises(ValueError):
        run_with_deadline(lambda: 1, 0)


def test_config5_secondary_arm_failure_keeps_headline(monkeypatch):
    """A secondary A/B arm whose compile/measure raises (e.g. a kernel
    lowering Mosaic rejects on new hardware) must be recorded in
    arm_errors and excluded — never crash the headline artifact.  The
    headline arm's own failure stays fatal."""
    import pytest

    import bench

    class FakeRunner:
        rates = {"pallas": 30000.0, "xla": 15000.0, "inc_xla": 45000.0}

        def __init__(self, cfg, points):
            self.cfg = cfg
            self.backend = cfg.median_backend

        def measure_barrier_rtt_ms(self):
            return 1.0

        def measure_device_only(self, iters):
            if self.backend == "inc_pallas":
                raise RuntimeError("Mosaic lowering rejected")
            return self.rates[self.backend]

        def measure_round(self, iters):
            return 300.0

        def measure_sync_p99(self):
            return 5.0

        def measure_link_put_ms(self):
            return 1.0

    class FakeDev:
        platform = "tpu"

    monkeypatch.setattr(bench, "_ChainRunner", FakeRunner)
    monkeypatch.setattr(bench.jax, "devices", lambda: [FakeDev()])
    out = bench.main(5, "pallas")
    ab = out["median_ab"]
    assert out["value"] == 30000.0
    assert ab["speedup"] == 2.0
    assert "inc_pallas" not in ab["rounds"]
    assert "Mosaic" in ab["arm_errors"]["inc_pallas"]
    # the surviving pinned-jnp arm still carries the continuity key
    assert ab["inc_vs_headline_speedup"] == 1.5
    assert "inc_pallas_vs_headline_speedup" not in ab
    assert "inc_pallas_vs_inc_xla_speedup" not in ab

    class CtorFailRunner(FakeRunner):
        # the realistic failure site: the constructor's WARMUP submit
        # compiles the step, where a Mosaic-rejected lowering raises
        def __init__(self, cfg, points):
            if cfg.median_backend == "inc_pallas":
                raise RuntimeError("Mosaic rejected at compile")
            super().__init__(cfg, points)

    monkeypatch.setattr(bench, "_ChainRunner", CtorFailRunner)
    out = bench.main(5, "pallas")
    ab = out["median_ab"]
    assert out["value"] == 30000.0
    assert "Mosaic rejected at compile" in ab["arm_errors"]["inc_pallas"]
    assert "inc_pallas" not in ab["rounds"]

    class FatalRunner(FakeRunner):
        def measure_device_only(self, iters):
            if self.backend == "pallas":
                raise RuntimeError("headline arm died")
            return 1.0

    monkeypatch.setattr(bench, "_ChainRunner", FatalRunner)
    with pytest.raises(RuntimeError, match="headline arm died"):
        bench.main(5, "pallas")


def test_decide_backends_keep_entry_displaces_degraded_flip():
    """ADVICE r5 #2: when a record's deep-window crossings exist but none
    clears the bar, an explicit flip=False keep entry (strongest ratio)
    must be emitted — so a healthier artifact can displace an earlier
    degraded-link record's flip under the strongest-evidence merge."""
    import importlib
    import os
    import sys

    sys.modules.pop("decide_backends", None)
    scripts_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    )
    sys.path.insert(0, scripts_dir)
    try:
        db = importlib.import_module("decide_backends")
    finally:
        sys.path.remove(scripts_dir)

    degraded = {  # link weather: inc barely "wins" at depth
        "device": "tpu",
        "deep_window_ab": {"512": {"inc_vs_best_sort_speedup": 1.31}},
    }
    healthy = {  # healthier rig: inc decisively LOSES at every depth
        "device": "tpu",
        "deep_window_ab": {
            "256": {"inc_vs_best_sort_speedup": 0.55},
            "512": {"inc_vs_best_sort_speedup": 0.61},
        },
    }
    # alone, the healthy record argues keep with its strongest ratio
    solo = db.analyze([healthy])
    thr = solo["recommendations"]["median_backend.tpu.window_threshold"]
    assert thr["flip"] is False
    assert thr["recommended"] == "pallas at every depth"
    assert thr["value"] == 0.55  # |log 0.55| > |log 0.61|

    # merged in either order, the healthy evidence (|log 0.55| > |log 1.31|)
    # displaces the degraded flip
    for records in ([degraded, healthy], [healthy, degraded]):
        merged = db.analyze(records)
        thr = merged["recommendations"]["median_backend.tpu.window_threshold"]
        assert thr["flip"] is False, records

    # a record with NO crossings at all still emits nothing
    empty = db.analyze([{"device": "tpu", "deep_window_ab": {}}])
    assert "median_backend.tpu.window_threshold" not in empty["recommendations"]

    # keep-entry strength comes from pro-keep evidence ONLY: a near-flip
    # record (1.40 at 256 but 0.98 at depth — fails the upward-closed
    # suffix) must carry its weak pro-keep ratio (0.98), not |log 1.40|,
    # so it can never decisively suppress a genuine flip elsewhere
    near_flip = {
        "device": "tpu",
        "deep_window_ab": {
            "256": {"inc_vs_best_sort_speedup": 1.40},
            "512": {"inc_vs_best_sort_speedup": 0.98},
        },
    }
    solo = db.analyze([near_flip])
    thr = solo["recommendations"]["median_backend.tpu.window_threshold"]
    assert thr["flip"] is False and thr["value"] == 0.98
    genuine_flip = {
        "device": "tpu",
        "deep_window_ab": {"512": {"inc_vs_best_sort_speedup": 1.25}},
    }
    for records in ([near_flip, genuine_flip], [genuine_flip, near_flip]):
        merged = db.analyze(records)
        thr = merged["recommendations"]["median_backend.tpu.window_threshold"]
        assert thr["flip"] is True, records

    # all-above-1 but sub-margin: a feeble keep rides the weakest ratio
    subm = db.analyze([
        {"device": "tpu",
         "deep_window_ab": {"512": {"inc_vs_best_sort_speedup": 1.03}}}
    ])
    thr = subm["recommendations"]["median_backend.tpu.window_threshold"]
    assert thr["flip"] is False and thr["value"] == 1.03


def test_bench_smoke_ingest():
    """`bench.py --smoke-ingest` — the tier-1 regression gate for the
    fused ingest path (config-9 A/B at seconds-scale CPU geometry): it
    must run anywhere without a device link and emit a well-formed
    artifact in which both ingest backends produced the same revolution
    count.  This pins the harness and the seam's liveness, not the
    speedup numbers — a 1.5-core CI container's timing is weather, and
    the bit-exactness contract lives in tests/test_fused_ingest.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-ingest"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "fused_ingest_bytes_to_output_scans_per_sec"
    assert out["smoke"] is True and out["device"] == "cpu"
    # both seams consumed identical bytes: identical revolution counts,
    # and every revolution actually flowed bytes -> filter output
    assert out["fused_revolutions"] == out["host_revolutions"] > 0
    assert out["value"] > 0 and out["host_scans_per_sec"] > 0
    # the overhead decomposition must be present and sane (the calibrated
    # shared chain step can't be free, and overheads can't be negative)
    assert out["chain_step_ms_per_rev"] > 0
    assert out["host_ingest_overhead_ms_per_rev"] >= 0
    assert out["fused_ingest_overhead_ms_per_rev"] >= 0
    assert out["ingest_overhead_speedup"] > 0


def test_bench_smoke_fleet_ingest():
    """`bench.py --smoke-fleet-ingest` — the tier-1 gate for the FLEET
    fused ingest path (config-10 A/B at seconds-scale CPU geometry).
    The structural O(N) -> O(1) claim is the assertion that matters: the
    fused arm's per-tick dispatch/transfer counts must be identical
    across the two fleet sizes while the host arm's grow with N (the
    bench itself raises on violation; this gate pins that the asserted
    artifact lands).  Wall-time numbers are 1.5-core-CI weather and are
    only sanity-bounded; bit-exactness lives in
    tests/test_fleet_fused_ingest.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-fleet-ingest"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "fleet_fused_ingest_bytes_to_scans_per_sec"
    assert out["smoke"] is True and out["device"] == "cpu"
    # the structural claim, re-checked from the artifact: constant fused
    # counts across fleet sizes, growing host counts, parity rev counts
    fleets = out["fleets"]
    assert len(fleets) == 2
    (small, big) = (fleets[k] for k in sorted(fleets, key=int))
    assert small["fused"]["dispatches_per_tick"] == \
        big["fused"]["dispatches_per_tick"]
    assert small["fused"]["h2d_per_tick"] == big["fused"]["h2d_per_tick"]
    assert big["host"]["dispatches_per_tick"] > \
        small["host"]["dispatches_per_tick"]
    assert out["structural"]["o1_claim_holds"] is True
    for f in fleets.values():
        assert f["host"]["revolutions"] == f["fused"]["revolutions"] > 0
        assert f["tick_step_ms"] > 0
        assert f["host_ingest_overhead_ms_per_tick"] >= 0
        assert f["fused_ingest_overhead_ms_per_tick"] >= 0
    # the decide_backends decision key and the startup meta must ride
    assert out["fleet_ingest_ab"]["ingest_overhead_speedup"] > 0
    assert out["startup"]["compilation_cache"] == {"enabled": False}
    assert out["startup"]["host_setup_precompile_s"] > 0
    assert out["startup"]["fused_setup_precompile_s"] > 0
    assert "ceiling_analysis" in out


def test_bench_smoke_super_tick():
    """`bench.py --smoke-super-tick` — the tier-1 gate for the T-tick
    SUPER-STEP lowering (config-11 drain A/B at seconds-scale CPU
    geometry).  The structural T -> 1 claim is the assertion that
    matters: the super arm must drain the backlog in ceil(ticks/T)
    compiled dispatches (2 staged transfers each) vs one per tick for
    the per-tick arm, at identical revolution counts (the bench itself
    raises on violation; this gate pins that the asserted artifact
    lands).  Wall-time numbers are 1.5-core-CI weather and only
    sanity-bounded; bit-exactness lives in tests/test_super_tick.py."""
    import json
    import math
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-super-tick"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "super_tick_drain_scans_per_sec"
    assert out["smoke"] is True and out["device"] == "cpu"
    # the structural claim, re-checked from the artifact
    t = out["super_tick"]
    ticks = out["ticks"]
    assert out["per_tick"]["dispatches"] == ticks
    assert out["super"]["dispatches"] == math.ceil(ticks / t)
    for arm in ("per_tick", "super"):
        assert out[arm]["h2d_transfers"] == 2 * out[arm]["dispatches"]
    assert out["structural"]["t_to_1_claim_holds"] is True
    # parity and liveness: both arms completed the same nonzero revs
    assert out["per_tick"]["revolutions"] == out["super"]["revolutions"] > 0
    assert out["value"] > 0 and out["per_tick"]["scans_per_sec"] > 0
    # the calibrated decomposition must be present and sane
    assert out["dispatch_floor_ms"] > 0
    assert out["predicted_saving_ms"] >= 0
    # the decide_backends decision key rides with its clamp flag
    assert out["super_tick_ab"]["drain_speedup"] > 0
    assert isinstance(out["super_tick_ab"]["overhead_clamped"], bool)
    assert "ceiling_analysis" in out


def test_decide_backends_super_tick_key():
    """The super_tick_max recommendation flips from config-11 evidence
    alone: TPU records past the bar recommend the T=8 default, CPU
    records and clamped decompositions never flip."""
    import importlib
    import os
    import sys

    sys.modules.pop("decide_backends", None)
    scripts_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    )
    sys.path.insert(0, scripts_dir)
    try:
        db = importlib.import_module("decide_backends")
    finally:
        sys.path.remove(scripts_dir)

    out = db.analyze([
        {"device": "tpu", "super_tick": 8,
         "super_tick_ab": {"drain_speedup": 2.4,
                           "per_dispatch_floor_ms": 4.0,
                           "overhead_clamped": False}},
        {"device": "cpu",  # CPU record: no decision weight
         "super_tick_ab": {"drain_speedup": 9.0,
                           "overhead_clamped": False}},
    ])
    rec = out["recommendations"]["super_tick_max.tpu"]
    assert rec["flip"] is True and rec["recommended"] == "8"
    assert rec["value"] == 2.4  # the TPU record, not the CPU 9.0
    assert out["evidence"]["super_tick_ab"]

    # the recommended T is the record's measured super_tick, not a
    # hardcoded constant (a rig override running T=4 must recommend 4)
    t4 = db.analyze([
        {"device": "tpu", "super_tick": 4,
         "super_tick_ab": {"drain_speedup": 3.0,
                           "overhead_clamped": False}},
    ])
    assert t4["recommendations"]["super_tick_max.tpu"]["recommended"] == "4"

    # a clamped decomposition records evidence but cannot flip
    clamped = db.analyze([
        {"device": "tpu",
         "super_tick_ab": {"drain_speedup": 50.0,
                           "overhead_clamped": True}},
    ])
    assert "super_tick_max.tpu" not in clamped["recommendations"]
    assert clamped["evidence"]["super_tick_ab"]

    # sub-margin TPU evidence keeps the disabled default
    keep = db.analyze([
        {"device": "tpu",
         "super_tick_ab": {"drain_speedup": 1.01,
                           "overhead_clamped": False}},
    ])
    rec = keep["recommendations"]["super_tick_max.tpu"]
    assert rec["flip"] is False and rec["recommended"] == "1"


def test_bench_smoke_mapping():
    """`bench.py --smoke-mapping` — the tier-1 gate for the SLAM
    front-end (config-12 A/B at seconds-scale CPU geometry).  The
    structural claims are what matters: ONE fused dispatch per fleet
    tick independent of fleet size, bit-exact host/fused parity, and
    the matcher tracking the synthetic drift (the bench itself raises
    on violation; this gate pins that the asserted artifact lands).
    Wall-time numbers are 1.5-core-CI weather and only sanity-bounded;
    kernel-level bit-exactness lives in tests/test_mapping.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-mapping"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "mapping_match_update_scans_per_sec"
    assert out["smoke"] is True and out["device"] == "cpu"
    # the structural claims, re-checked from the artifact
    assert out["fused"]["dispatches"] == out["ticks"]
    assert out["structural"]["one_dispatch_claim_holds"] is True
    assert out["structural"]["bit_exact_parity_holds"] is True
    # accuracy: the matcher held onto the synthetic drift
    assert 0 <= out["pose_err_cells"] <= 8.0
    # liveness + calibrated decomposition present and sane
    assert out["value"] > 0 and out["host"]["scans_per_sec"] > 0
    assert out["dispatch_floor_ms"] > 0
    # the decide_backends decision key rides with its clamp flag
    assert out["mapping_ab"]["match_speedup"] > 0
    assert isinstance(out["mapping_ab"]["overhead_clamped"], bool)
    assert "ceiling_analysis" in out


def test_bench_smoke_chaos():
    """`bench.py --smoke-chaos` — the tier-1 gate for the fault-
    tolerance subsystem (config-13 degraded-fleet A/B at seconds-scale
    CPU geometry).  The structural claims are what matters: one
    dispatch per tick with K streams quarantined, zero recompiles and
    zero implicit transfers across the quarantine -> rejoin cycle,
    byte-for-byte fault isolation of the healthy streams (the bench
    itself raises on violation; this gate pins that the asserted
    artifact lands).  The healthy-throughput ratio is 1.5-core-CI
    weather and only floor-bounded inside the bench; the bit-exact
    chaos parity contract lives in tests/test_chaos.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-chaos"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(13)
    assert out["smoke"] is True and out["device"] == "cpu"
    # the structural claims, re-checked from the artifact
    s = out["structural"]
    assert s["one_dispatch_per_tick"] is True
    assert s["zero_recompiles"] is True
    assert s["zero_implicit_transfers"] is True
    assert s["fault_isolation_bit_exact"] is True
    assert s["quarantine_rejoin_completed"] is True
    # every faulty arm quarantined exactly its faulty streams and
    # completed at least one rejoin each (the bench itself asserts the
    # degraded lane completed the same healthy revolutions as its
    # tick-paired baseline lane)
    for k in out["faulty_arms"]:
        if k == 0:
            continue  # the baseline rides inside each pair now
        arm = out["degraded"][str(k)]
        assert arm["quarantined"] == list(range(k))
        assert arm["rejoins"] >= k
        assert arm["healthy_revs"] > 0
    # liveness + the honestly-recorded 5% verdict (the bench itself
    # asserts the spike-robust steady-state ratio >= 0.9 in smoke mode)
    assert out["value"] > 0 and out["worst_steady_ratio"] >= 0.9
    assert isinstance(out["within_5pct"], bool)
    assert isinstance(out["worst_healthy_ratio"], float)
    assert "ceiling_analysis" in out


def test_bench_smoke_failover():
    """`bench.py --smoke-failover` — the tier-1 gate for the elastic-
    fleet failover path (config-15 shard-loss A/B at seconds-scale CPU
    geometry).  The structural claims are what matters: the full
    kill -> evacuate -> re-admit cycle completes under the steady-state
    guard (zero recompiles / zero implicit transfers, evacuation and
    snapshot pulls included), survivors stay byte-for-byte on the
    unkilled baseline pod, and every migrated stream matches its
    host-golden replay (the bench itself raises on violation; this
    gate pins that the asserted artifact lands).  The survivor
    throughput ratio is 1.5-core-CI weather and only floor-bounded
    inside the bench; the bit-exact failover contract incl. final maps
    lives in tests/test_failover.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-failover"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(15)
    assert out["smoke"] is True and out["device"] == "cpu"
    # the structural claims, re-checked from the artifact
    s = out["structural"]
    assert s["one_dispatch_per_tick_per_survivor"] is True
    assert s["zero_recompiles"] is True
    assert s["zero_implicit_transfers"] is True
    assert s["fault_isolation_bit_exact"] is True
    assert s["migrated_replay_bit_exact"] is True
    assert s["evacuate_readmit_completed"] is True
    # the acceptance topology: 1 of 4 shards killed, its 2 streams
    # migrated, the other 6 survivors carried the metric
    assert out["shards"] == 4 and out["streams"] == 8
    assert out["migrated"] == [1, 5]
    assert len(out["survivors"]) == 6
    # liveness + the floor the bench itself asserts in smoke mode
    assert out["value"] > 0 and out["survivor_steady_ratio"] >= 0.9
    # the evacuation-latency decomposition rides the artifact
    ev = out["evacuation"]
    assert ev["snapshot_pull_ms"] >= 0.0
    assert ev["restore_scatter_ms"] > 0.0
    assert ev["first_tick_ms"] > 0.0
    # the decision key rides with its clamp flag
    assert "survivor_steady_ratio" in out["failover_ab"]
    assert isinstance(out["failover_ab"]["ratio_clamped"], bool)
    assert "ceiling_analysis" in out


def test_bench_smoke_deskew():
    """`bench.py --smoke-deskew` — the tier-1 gate for the de-skew +
    sweep-reconstruction stage (config-16 A/B at seconds-scale CPU
    geometry).  The structural claims are what matters: one ingest
    dispatch per tick PER ARM (the de-skew/reconstruction stages ride
    inside the existing fused program), >= 2x map-update multiplication
    per physical revolution, zero-motion identity on the static scene,
    and bit-exact host-twin replay of the reconstructed sweeps and the
    de-skewed revolutions (the bench itself raises on violation; this
    gate pins that the asserted artifact lands).  The tick-time ratio
    is 1.5-core-CI weather and unasserted; the bit-exact de-skew
    contract across every lowering lives in tests/test_deskew.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-deskew"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(16)
    assert out["smoke"] is True and out["device"] == "cpu"
    # the structural claims, re-checked from the artifact
    s = out["structural"]
    assert s["one_dispatch_per_tick"] is True
    assert s["zero_recompiles"] is True
    assert s["zero_implicit_transfers"] is True
    assert s["update_multiplication"] is True
    assert s["zero_motion_identity"] is True
    assert s["host_twin_bit_exact"] is True
    # the R× claim the config exists for: the reconstruct arm delivered
    # at least 2 updates per revolution while the arms completed the
    # SAME revolutions (the bench asserts equality)
    assert out["updates"]["multiplier"] >= 2.0
    assert out["updates"]["reconstruct"] >= 2 * out["revolutions"]
    assert out["value"] > 0
    # the decision key rides with its clamp flag
    assert "update_multiplier" in out["deskew_ab"]
    assert "steady_tick_ratio" in out["deskew_ab"]
    assert isinstance(out["deskew_ab"]["ratio_clamped"], bool)
    assert "ceiling_analysis" in out


def test_bench_smoke_loop_close():
    """`bench.py --smoke-loop-close` — the tier-1 gate for the SLAM
    back-end (config-17 A/B at seconds-scale CPU geometry).  The
    structural/accuracy claims are what matters: pose-graph-corrected
    end-pose error <= 2 map cells on the drift-injected
    return-to-start trace while the front-end-only baseline carries
    the full injected drift, exactly one engine dispatch per
    closure-check tick, bit-exact host/fused parity, and zero
    recompiles / implicit transfers under the steady-state guard (the
    bench itself raises on violation; this gate pins that the asserted
    artifact lands).  The wall ratios are 1.5-core-CI weather; the
    bit-exact back-end contract lives in tests/test_loop_close.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-loop-close"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(17)
    assert out["smoke"] is True and out["device"] == "cpu"
    # the structural claims, re-checked from the artifact
    s = out["structural"]
    assert s["one_dispatch_per_check_holds"] is True
    assert s["bit_exact_parity_holds"] is True
    assert s["drift_bounded_holds"] is True
    # the accuracy pair the config exists for
    assert out["corrected_end_err_cells"] <= 2.0
    assert out["baseline_end_err_cells"] >= 4.0
    assert out["closures_accepted"] > 0
    assert out["fused"]["dispatches"] == out["fused"]["check_ticks"]
    assert out["value"] > 0
    # the decision key rides with its clamp flag
    ab = out["loop_close_ab"]
    assert "backend_speedup" in ab and "steady_tick_ratio" in ab
    assert isinstance(ab["overhead_clamped"], bool)
    assert "ceiling_analysis" in out


def test_decide_backends_loop_close_key():
    """The config-17 key drives TWO mappings: `loop_backend` flips
    host -> fused on an unclamped TPU wall ratio over the margin, and
    `loop_enable` flips only when the corrected error meets the 2-cell
    bar at a >= 0.90 tick ratio — CPU records and clamped ratios never
    flip either."""
    import importlib
    import sys as _sys

    _sys.modules.pop("decide_backends", None)
    _sys.path.insert(0, "scripts")
    try:
        db = importlib.import_module("decide_backends")
    finally:
        _sys.path.pop(0)

    def rec(dev, speedup, err, ratio, clamped=False):
        return {
            "device": dev,
            "loop_close_ab": {
                "backend_speedup": speedup,
                "corrected_end_err_cells": err,
                "steady_tick_ratio": ratio,
                "baseline_end_err_cells": 12.0,
                "overhead_clamped": clamped,
            },
        }

    # clean TPU record: backend flips on the ratio, enable on the pair
    got = db.analyze([rec("tpu", 5.5, 1.2, 0.95)])
    r = got["recommendations"]["loop_backend.tpu"]
    assert r["flip"] is True and r["recommended"] == "fused"
    r = got["recommendations"]["loop_enable.tpu"]
    assert r["flip"] is True and r["recommended"] == "true"
    # CPU record: reported, never flips
    got = db.analyze([rec("cpu", 9.9, 0.5, 1.0)])
    assert "loop_backend.tpu" not in got["recommendations"]
    assert "loop_enable.tpu" not in got["recommendations"]
    assert got["non_tpu_ignored"]
    # clamped: evidence only — neither mapping flips
    got = db.analyze([rec("tpu", 5.5, 1.2, 0.95, clamped=True)])
    assert "loop_backend.tpu" not in got["recommendations"]
    assert got["recommendations"]["loop_enable.tpu"]["flip"] is False
    # correction missing the 2-cell bar: loop_enable stays off
    got = db.analyze([rec("tpu", 5.5, 3.0, 0.95)])
    assert got["recommendations"]["loop_enable.tpu"]["flip"] is False
    # tick ratio below the floor: loop_enable stays off
    got = db.analyze([rec("tpu", 5.5, 1.2, 0.5)])
    assert got["recommendations"]["loop_enable.tpu"]["flip"] is False


def test_bench_smoke_fused_mapping():
    """`bench.py --smoke-fused-mapping` — the tier-1 gate for the
    one-dispatch stack (config-18 A/B at seconds-scale CPU geometry).
    The structural claims are what matters: T ticks of ingest + T
    mapper dispatches collapse to ceil(ticks/T) compiled dispatches
    with ZERO separate mapper dispatches (mapping rides the ingest
    scan carry), zero recompiles/implicit transfers under the
    steady-state guard, and byte-equal trajectories + final maps vs
    the two-dispatch baseline (the bench itself raises on violation;
    this gate pins that the asserted artifact lands).  The group-time
    ratio is 1.5-core-CI weather and unasserted; the bit-exact
    route-parity contract across every lowering lives in
    tests/test_fused_mapping.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-fused-mapping"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(18)
    assert out["smoke"] is True and out["device"] == "cpu"
    s = out["structural"]
    assert s["one_dispatch_per_super_tick"] is True
    assert s["zero_mapper_dispatches"] is True
    assert s["zero_recompiles"] is True
    assert s["zero_implicit_transfers"] is True
    assert s["byte_equal_trajectories"] is True
    assert s["byte_equal_maps"] is True
    # the collapse the config exists for: T+T baseline dispatches per
    # group against exactly one fused dispatch per group
    d = out["dispatches"]
    assert d["fused_total"] == out["groups"]
    assert d["baseline_ingest"] == out["groups"] * out["super_tick"]
    assert d["baseline_mapper"] > 0
    assert out["updates"] > 0 and out["value"] > 0
    # the decision key rides with its clamp flag
    assert "steady_group_ratio" in out["fused_mapping_ab"]
    assert "dispatch_collapse" in out["fused_mapping_ab"]
    assert isinstance(out["fused_mapping_ab"]["ratio_clamped"], bool)
    assert "ceiling_analysis" in out


def test_decide_backends_fused_mapping_key():
    """The fused_mapping_backend recommendation flips from config-18
    evidence alone: an unclamped TPU record with the steady group
    ratio >= 0.95 recommends the flip (the dispatch collapse is
    structural — parity throughput IS the win); CPU records, clamped
    ratios and below-floor ratios never flip."""
    import importlib
    import sys as _sys

    _sys.path.insert(0, "scripts")
    try:
        db = importlib.import_module("decide_backends")
    finally:
        _sys.path.pop(0)

    def rec(dev, ratio, clamped=False):
        return {
            "device": dev,
            "fused_mapping_ab": {
                "steady_group_ratio": ratio,
                "dispatch_collapse": 16.0,
                "ratio_clamped": clamped,
            },
        }

    got = db.analyze([rec("tpu", 1.02)])
    r = got["recommendations"]["fused_mapping_backend.tpu"]
    assert r["flip"] is True and r["recommended"] == "fused"
    # CPU record: reported, never flips
    got = db.analyze([rec("cpu", 1.3)])
    assert "fused_mapping_backend.tpu" not in got["recommendations"]
    assert got["non_tpu_ignored"]
    # clamped ratio: evidence only
    got = db.analyze([rec("tpu", 1.3, clamped=True)])
    assert "fused_mapping_backend.tpu" not in got["recommendations"]
    # below the floor: the in-program update is eating the group rate
    got = db.analyze([rec("tpu", 0.7)])
    assert got["recommendations"]["fused_mapping_backend.tpu"]["flip"] is False
    # floor-asymmetric strength merge: committed degradation evidence
    # outweighs a later clean record's parity strength
    got = db.analyze([rec("tpu", 0.5), rec("tpu", 1.0)])
    assert got["recommendations"]["fused_mapping_backend.tpu"]["flip"] is False


def test_bench_smoke_elastic_serving():
    """`bench.py --smoke-elastic-serving` — the tier-1 gate for the
    traffic-shaped serving plane (config-19 A/B at seconds-scale CPU
    geometry).  The structural claims are what matters: per-rung
    dispatch accounting with the burst collapse (the adaptive arm
    issues strictly fewer compiled dispatches over the same trace),
    bounded per-stream backlog with shadow-checked oldest-tick sheds,
    byte-equal trajectories across the adaptive/static arms AND the
    host golden, byte-rate-weighted heaviest-first evacuation, and
    zero recompiles/implicit transfers across rung switches and a
    chaos shard kill (the bench itself raises on violation; this gate
    pins that the asserted artifact lands).  The p99 ratio is
    1.5-core-CI weather at smoke geometry and floor-checked only; the
    asserted WIN bar applies to full runs."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-elastic-serving"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(19)
    assert out["smoke"] is True and out["device"] == "cpu"
    s = out["structural"]
    for claim in (
        "per_rung_accounting", "static_arm_rung1_only",
        "adaptive_reached_top_rung", "dispatch_collapse",
        "bounded_backlog", "shed_policy_matches_shadow",
        "byte_equal_arms", "byte_equal_host_golden",
        "weighted_evacuation", "zero_recompiles",
        "zero_implicit_transfers",
    ):
        assert s[claim] is True, claim
    # the collapse the config exists for: the static arm dispatched
    # only rung 1, the adaptive arm strictly fewer dispatches total
    assert set(out["rung_dispatches"]["static"]) == {"1"}
    assert any(
        int(r_) > 1 and n > 0
        for r_, n in out["rung_dispatches"]["adaptive"].items()
    )
    assert (
        out["dispatch_totals"]["adaptive"] < out["dispatch_totals"]["static"]
    )
    # the admission bound held and was exercised
    adm = out["admission"]
    assert adm["max_depth_seen"] <= adm["bound_ticks"]
    assert adm["sheds_total"] > 0
    assert out["scans"] > 0 and out["value"] > 0
    # the decision key rides with its clamp flag
    assert "p99_speedup" in out["elastic_serving_ab"]
    assert isinstance(out["elastic_serving_ab"]["ratio_clamped"], bool)
    assert "ceiling_analysis" in out


def test_decide_backends_elastic_serving_key():
    """The sched_rungs ladder recommendation flips from config-19
    evidence alone: an unclamped TPU record with p99_speedup above the
    noise margin recommends the measured ladder; CPU records and
    clamped ratios never flip, and the floor-asymmetric strength merge
    keeps an above-parity noise record from displacing committed
    degradation evidence (the failover_ab discipline)."""
    import importlib
    import sys as _sys

    _sys.path.insert(0, "scripts")
    try:
        db = importlib.import_module("decide_backends")
    finally:
        _sys.path.pop(0)

    def rec(dev, speedup, clamped=False):
        return {
            "device": dev,
            "elastic_serving_ab": {
                "p99_speedup": speedup,
                "rungs": [1, 2, 4, 8],
                "shards": 4,
                "ratio_clamped": clamped,
            },
        }

    got = db.analyze([rec("tpu", 1.2)])
    r = got["recommendations"]["sched_rungs.tpu"]
    assert r["flip"] is True and r["recommended"] == "1,2,4,8"
    assert r["measured"] == 1.2
    # CPU record: reported, never flips
    got = db.analyze([rec("cpu", 1.5)])
    assert "sched_rungs.tpu" not in got["recommendations"]
    assert got["non_tpu_ignored"]
    # clamped ratio: evidence only
    got = db.analyze([rec("tpu", 1.5, clamped=True)])
    assert "sched_rungs.tpu" not in got["recommendations"]
    assert got["evidence"]["elastic_serving_ab"]
    # below the margin: keep the static default
    got = db.analyze([rec("tpu", 1.01)])
    assert got["recommendations"]["sched_rungs.tpu"]["flip"] is False
    # floor-asymmetric strength merge: a committed degradation record
    # outweighs a later above-parity noise record
    got = db.analyze([rec("tpu", 0.6), rec("tpu", 1.3)])
    assert got["recommendations"]["sched_rungs.tpu"]["flip"] is False


def test_bench_smoke_async_serving():
    """`bench.py --smoke-async-serving` — the tier-1 gate for the
    link-latency-hiding serving plane (config-20 A/B at seconds-scale
    CPU geometry).  The structural claims are what matters: per-(rung,
    bucket) dispatch accounting, the double buffer's staging/compute
    overlap engaging on the async arm ONLY, the bucket ladder
    collapsing AND recovering mid-run with zero recompiles, a fully
    warmup-seeded latency model, bounded shadow-checked admission, and
    byte-equal trajectories across the async/PR14 arms AND the host
    golden (the bench itself raises on violation; this gate pins that
    the asserted artifact lands).  The p99 ratio is 1.5-core-CI
    weather at smoke geometry and floor-checked only; the asserted WIN
    bar applies to full runs."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-async-serving"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(20)
    assert out["smoke"] is True and out["device"] == "cpu"
    s = out["structural"]
    for claim in (
        "per_rung_bucket_accounting", "reached_top_rung",
        "bucket_ladder_moved_both_ways", "pr14_arm_static",
        "async_overlap_engaged", "latency_model_fully_seeded",
        "bounded_backlog", "shed_policy_matches_shadow",
        "byte_equal_arms", "byte_equal_host_golden",
        "zero_recompiles", "zero_implicit_transfers",
    ):
        assert s[claim] is True, claim
    # the overlap and the ladder are async-arm effects ONLY: the PR14
    # arm must show the static pre-PR-16 behavior on the same trace
    assert out["staging_overlap_hits"]["async"] > 0
    assert out["staging_overlap_hits"]["pr14"] == 0
    assert out["bucket_switches"]["async"] >= 2  # collapse AND recovery
    assert out["bucket_switches"]["pr14"] == 0
    # every warmed (rung, bucket) executable is priced
    want = {
        f"T{r_}xM{b}" for r_ in out["rungs"] for b in out["buckets"]
    }
    assert set(out["latency_model_ms"]) >= want
    # per-(rung, bucket) accounting landed for both arms
    for arm in ("pr14", "async"):
        assert out["rung_bucket_dispatches"][arm]
        assert all(
            n >= 0 for n in out["rung_bucket_dispatches"][arm].values()
        )
    # the admission bound held and was exercised
    adm = out["admission"]
    assert adm["max_depth_seen"] <= adm["bound_ticks"]
    assert adm["sheds_total"] > 0
    assert out["scans"] > 0 and out["value"] > 0
    # the decision key rides with its clamp flag
    ab = out["async_serving_ab"]
    assert "p99_speedup" in ab
    assert isinstance(ab["ratio_clamped"], bool)
    assert ab["overlap_hits"] > 0 and ab["bucket_switches"] >= 2
    assert "ceiling_analysis" in out


def test_decide_backends_async_serving_key():
    """The staging_double_buffer recommendation flips from config-20
    evidence alone: an unclamped TPU record with p99_speedup above the
    noise margin recommends the double-buffered staging path (with its
    measured bucket ladder); CPU records and clamped ratios never
    flip, and the floor-asymmetric strength merge keeps an
    above-parity noise record from displacing committed degradation
    evidence (the elastic_serving_ab discipline)."""
    import importlib
    import sys as _sys

    _sys.path.insert(0, "scripts")
    try:
        db = importlib.import_module("decide_backends")
    finally:
        _sys.path.pop(0)

    def rec(dev, speedup, clamped=False):
        return {
            "device": dev,
            "async_serving_ab": {
                "p99_speedup": speedup,
                "buckets": [4, 16],
                "rungs": [1, 2, 4, 8],
                "overlap_hits": 40,
                "bucket_switches": 4,
                "ratio_clamped": clamped,
            },
        }

    got = db.analyze([rec("tpu", 1.2)])
    r = got["recommendations"]["staging_double_buffer.tpu"]
    assert r["flip"] is True
    assert r["recommended"] == "double-buffered, bucket_rungs=4,16"
    assert r["measured"] == 1.2
    # CPU record: reported, never flips (a linkless rig has no H2D
    # latency to hide — its ratio prices bookkeeping)
    got = db.analyze([rec("cpu", 1.5)])
    assert "staging_double_buffer.tpu" not in got["recommendations"]
    assert got["non_tpu_ignored"]
    # clamped ratio: evidence only
    got = db.analyze([rec("tpu", 1.5, clamped=True)])
    assert "staging_double_buffer.tpu" not in got["recommendations"]
    assert got["evidence"]["async_serving_ab"]
    # below the margin: keep the synchronous PR14 staging
    got = db.analyze([rec("tpu", 1.01)])
    r = got["recommendations"]["staging_double_buffer.tpu"]
    assert r["flip"] is False
    assert "synchronous" in r["recommended"]
    # floor-asymmetric strength merge: a committed degradation record
    # outweighs a later above-parity noise record
    got = db.analyze([rec("tpu", 0.6), rec("tpu", 1.3)])
    assert (
        got["recommendations"]["staging_double_buffer.tpu"]["flip"]
        is False
    )


def test_bench_smoke_pod_scaleout():
    """`bench.py --smoke-pod-scaleout` — the tier-1 gate for the
    pod-of-pods serving plane (config-21 A/B at seconds-scale CPU
    geometry).  The structural claims are what matters: cross-shard
    stealing moving WHOLE deep queues onto sibling lanes with the
    accounting identity and zero staging drops, a full autoscale
    park/re-admit cycle with nothing left parked, an inert static
    arm, bounded shadow-checked admission, and byte-equal
    trajectories across the pod/static arms AND the host golden (the
    bench itself raises on violation; this gate pins that the
    asserted artifact lands).  The p99 ratio is steal-neutral by
    construction on a serializing CPU rig and catastrophe-floored
    only; the asserted WIN bar applies to full on-chip runs."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-pod-scaleout"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(21)
    assert out["smoke"] is True and out["device"] == "cpu"
    s = out["structural"]
    for claim in (
        "steals_moved_whole_deep_queues", "steal_accounting_identity",
        "no_steal_drops", "static_arm_inert", "full_scale_cycle",
        "all_shards_unparked_at_end", "bounded_backlog",
        "shed_policy_matches_shadow", "byte_equal_arms",
        "byte_equal_host_golden", "zero_recompiles",
        "zero_implicit_transfers",
    ):
        assert s[claim] is True, claim
    # the steal counters carry the accounting identity the bench
    # asserted: every steal_log row is (dst, src, stream, n) with the
    # deep shard as the ONE donor
    assert out["steals"] > 0
    assert out["steal_ticks"] == sum(e[3] for e in out["steal_log"])
    assert len(out["steal_log"]) == out["steals"]
    assert all(e[1] == 0 and e[0] != 0 for e in out["steal_log"])
    assert out["steal_drops"] == 0
    # the full scale cycle: the park precedes the re-admission
    downs = [e for e in out["scale_events"] if e[1] == "down"]
    ups = [e for e in out["scale_events"] if e[1] == "up"]
    assert downs and ups and downs[0][0] < ups[0][0]
    # the admission bound held (no shed is scheduled in this config —
    # the skew is a burst, not an outage)
    adm = out["admission"]
    assert adm["max_depth_seen"] <= adm["bound_ticks"]
    assert out["scans"] > 0 and out["value"] > 0
    # the decision key rides with its clamp flag
    ab = out["pod_scaleout_ab"]
    assert "p99_speedup" in ab
    assert isinstance(ab["ratio_clamped"], bool)
    assert ab["steals"] > 0 and ab["scale_downs"] >= 1
    assert ab["scale_ups"] >= 1
    assert "ceiling_analysis" in out


def test_decide_backends_pod_scaleout_key():
    """The pod_scaleout recommendation flips from config-21 evidence
    alone: an unclamped TPU record with p99_speedup above the noise
    margin recommends turning stealing + the autoscaler on; CPU
    records and clamped ratios never flip, and the floor-asymmetric
    strength merge keeps an above-parity noise record from displacing
    committed degradation evidence (the async_serving_ab
    discipline)."""
    import importlib
    import sys as _sys

    _sys.path.insert(0, "scripts")
    try:
        db = importlib.import_module("decide_backends")
    finally:
        _sys.path.pop(0)

    def rec(dev, speedup, clamped=False):
        return {
            "device": dev,
            "pod_scaleout_ab": {
                "p99_speedup": speedup,
                "steals": 12,
                "steal_ticks": 48,
                "scale_downs": 1,
                "scale_ups": 1,
                "hosts": 2,
                "ratio_clamped": clamped,
            },
        }

    got = db.analyze([rec("tpu", 1.2)])
    r = got["recommendations"]["pod_scaleout.tpu"]
    assert r["flip"] is True
    assert r["recommended"] == "steal + autoscale on"
    assert r["measured"] == 1.2
    # CPU record: reported, never flips (a one-process rig serializes
    # the shard drains — its per-tick max prices relocation, not the
    # reclaimed idle lanes)
    got = db.analyze([rec("cpu", 1.5)])
    assert "pod_scaleout.tpu" not in got["recommendations"]
    assert got["non_tpu_ignored"]
    # clamped ratio: evidence only
    got = db.analyze([rec("tpu", 1.5, clamped=True)])
    assert "pod_scaleout.tpu" not in got["recommendations"]
    assert got["evidence"]["pod_scaleout_ab"]
    # below the margin: keep the static pod
    got = db.analyze([rec("tpu", 1.01)])
    r = got["recommendations"]["pod_scaleout.tpu"]
    assert r["flip"] is False
    assert "static pod" in r["recommended"]
    # floor-asymmetric strength merge: a committed degradation record
    # outweighs a later above-parity noise record
    got = db.analyze([rec("tpu", 0.6), rec("tpu", 1.3)])
    assert (
        got["recommendations"]["pod_scaleout.tpu"]["flip"] is False
    )


def test_bench_smoke_map_serving():
    """`bench.py --smoke-map-serving` — the tier-1 gate for the
    shared-world mapping plane (config-22 A/B at seconds-scale CPU
    geometry).  The structural claims are what matters: a served tile
    read moves ZERO dispatch counters, the device merge is byte-equal
    to the numpy oracle under shuffled orders and split partial sums
    (the cross-shard case), eviction keeps resident bytes under the
    closed-form bound, the served grid sits within the quantization
    error bound with level-0 cells exactly zero, the published
    payload beats the dense int32 grid by >= 3x, and the drain's scan
    outputs are byte-equal across the tiles/pull arms (the bench
    itself raises on violation; this gate pins that the asserted
    artifact lands).  The read-latency ratio is a catastrophe floor
    on a one-process CPU rig; the latency headline belongs to
    on-chip captures."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-map-serving"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(22)
    assert out["smoke"] is True and out["device"] == "cpu"
    s = out["structural"]
    for claim in (
        "byte_equal_arms", "dispatch_count_identity",
        "reads_moved_no_dispatch", "merge_order_independent",
        "cross_shard_partial_sums_equal",
        "bounded_residency_with_evictions", "quant_error_within_bound",
        "compression_over_3x", "zero_recompiles",
        "zero_implicit_transfers",
    ):
        assert s[claim] is True, claim
    # the world ledger: membership filled past the cap (evictions
    # fired), snapshots published, residency stayed under the
    # closed-form bound
    assert out["merges"] > out["evictions"] > 0
    assert out["serving_version"] >= 1
    assert out["resident_bytes_max"] <= out["resident_bytes_bound"]
    # the capacity headline: RLE-over-quantized beats the dense int32
    # grid it replaces
    assert out["compression_ratio"] >= 3.0
    assert 0 < out["payload_bytes"] < out["raw_bytes"]
    assert out["paired_reads"] > 0 and out["value"] > 0
    # the decision key rides with its clamp flag
    ab = out["map_serving_ab"]
    assert "read_speedup" in ab
    assert isinstance(ab["ratio_clamped"], bool)
    assert ab["compression_ratio"] >= 3.0
    assert ab["merges"] > 0 and ab["evictions"] > 0
    assert "ceiling_analysis" in out


def test_decide_backends_map_serving_key():
    """The map_serving recommendation flips from config-22 evidence
    alone: an unclamped TPU record with read_speedup above the noise
    margin recommends the world map + tile snapshot serving for map
    consumers; CPU records and clamped ratios never flip, and the
    floor-asymmetric strength merge keeps an above-parity noise
    record from displacing committed degradation evidence (the
    pod_scaleout_ab discipline)."""
    import importlib
    import sys as _sys

    _sys.path.insert(0, "scripts")
    try:
        db = importlib.import_module("decide_backends")
    finally:
        _sys.path.pop(0)

    def rec(dev, speedup, clamped=False):
        return {
            "device": dev,
            "map_serving_ab": {
                "read_speedup": speedup,
                "compression_ratio": 12.5,
                "merges": 18,
                "evictions": 10,
                "ratio_clamped": clamped,
            },
        }

    got = db.analyze([rec("tpu", 4.0)])
    r = got["recommendations"]["map_serving.tpu"]
    assert r["flip"] is True
    assert r["recommended"] == "world map + tile snapshot serving"
    assert r["measured"] == 4.0
    # CPU record: reported, never flips (the pull baseline crosses a
    # host memcpy on a one-process rig, not a device link)
    got = db.analyze([rec("cpu", 7.0)])
    assert "map_serving.tpu" not in got["recommendations"]
    assert got["non_tpu_ignored"]
    # clamped ratio: evidence only
    got = db.analyze([rec("tpu", 7.0, clamped=True)])
    assert "map_serving.tpu" not in got["recommendations"]
    assert got["evidence"]["map_serving_ab"]
    # below the margin: keep the pulls
    got = db.analyze([rec("tpu", 1.01)])
    r = got["recommendations"]["map_serving.tpu"]
    assert r["flip"] is False
    assert "pulls" in r["recommended"]
    # floor-asymmetric strength merge: a committed degradation record
    # outweighs a later above-parity noise record
    got = db.analyze([rec("tpu", 0.6), rec("tpu", 1.3)])
    assert (
        got["recommendations"]["map_serving.tpu"]["flip"] is False
    )


def test_decide_backends_deskew_key():
    """The deskew_enable recommendation flips from config-16 evidence
    alone: an unclamped TPU record with the update multiplier >= 2x AND
    the paired tick ratio >= 0.90 recommends the flip; CPU records,
    clamped ratios, sub-2x multipliers and below-floor ratios never
    flip."""
    import importlib
    import sys as _sys

    _sys.path.insert(0, "scripts")
    try:
        db = importlib.import_module("decide_backends")
    finally:
        _sys.path.pop(0)

    def rec(dev, mult, ratio, clamped=False):
        return {
            "device": dev,
            "deskew_ab": {
                "update_multiplier": mult,
                "steady_tick_ratio": ratio,
                "ratio_clamped": clamped,
            },
        }

    # clean TPU record above both bars -> flip
    got = db.analyze([rec("tpu", 2.5, 0.97)])
    r = got["recommendations"]["deskew_enable.tpu"]
    assert r["flip"] is True and r["recommended"] == "true"
    # CPU record: reported, never flips
    got = db.analyze([rec("cpu", 3.0, 1.0)])
    assert "deskew_enable.tpu" not in got["recommendations"]
    assert got["non_tpu_ignored"]
    # clamped ratio: evidence only
    got = db.analyze([rec("tpu", 2.5, 0.97, clamped=True)])
    assert "deskew_enable.tpu" not in got["recommendations"]
    # sub-2x multiplier: no flip
    got = db.analyze([rec("tpu", 1.5, 0.99)])
    assert got["recommendations"]["deskew_enable.tpu"]["flip"] is False
    # below the tick-ratio floor: no flip (the extra mapper work is
    # eating the fleet rate)
    got = db.analyze([rec("tpu", 2.5, 0.7)])
    assert got["recommendations"]["deskew_enable.tpu"]["flip"] is False
    # floor-asymmetric strength: a committed degradation record is not
    # displaced by a later clean record's parity strength alone when
    # the degradation evidence is stronger
    got = db.analyze([rec("tpu", 2.5, 0.5), rec("tpu", 2.5, 0.97)])
    assert got["recommendations"]["deskew_enable.tpu"]["flip"] is False


def test_decide_backends_failover_key():
    """The shard_count recommendation flips from config-15 evidence
    alone: an unclamped TPU record at or above the 0.95 survivor floor
    recommends the measured pod width; CPU records, clamped ratios and
    below-floor records never flip — and a record showing real
    survivor degradation displaces a clean parity record (strength is
    distance from parity)."""
    import importlib
    import os
    import sys

    sys.modules.pop("decide_backends", None)
    scripts_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    )
    sys.path.insert(0, scripts_dir)
    try:
        db = importlib.import_module("decide_backends")
    finally:
        sys.path.remove(scripts_dir)

    out = db.analyze([
        {"device": "tpu",
         "failover_ab": {"survivor_steady_ratio": 0.99, "shards": 4,
                         "streams": 8, "ratio_clamped": False}},
        {"device": "cpu",  # CPU record: no decision weight
         "failover_ab": {"survivor_steady_ratio": 1.02, "shards": 4,
                         "streams": 8, "ratio_clamped": False}},
    ])
    rec = out["recommendations"]["shard_count.tpu"]
    assert rec["flip"] is True and rec["recommended"] == "4"
    # a flip entry carries parity strength (the floor discipline: its
    # strength must come from evidence AGAINST the flip, of which a
    # clean record has none); the measured ratio rides separately
    assert rec["value"] == 1.0
    assert rec["measured"] == 0.99  # the TPU record, not the CPU one
    assert out["evidence"]["failover_ab"]

    # a clamped ratio records evidence but cannot flip
    clamped = db.analyze([
        {"device": "tpu",
         "failover_ab": {"survivor_steady_ratio": 1.0, "shards": 4,
                         "ratio_clamped": True}},
    ])
    assert "shard_count.tpu" not in clamped["recommendations"]
    assert clamped["evidence"]["failover_ab"]

    # below the survivor floor: the single-shard default holds
    keep = db.analyze([
        {"device": "tpu",
         "failover_ab": {"survivor_steady_ratio": 0.80, "shards": 4,
                         "ratio_clamped": False}},
    ])
    rec = keep["recommendations"]["shard_count.tpu"]
    assert rec["flip"] is False and rec["recommended"] == "1"

    # degradation evidence outweighs parity evidence in the merge
    mixed = db.analyze([
        {"device": "tpu",
         "failover_ab": {"survivor_steady_ratio": 0.999, "shards": 4,
                         "ratio_clamped": False}},
        {"device": "tpu",
         "failover_ab": {"survivor_steady_ratio": 0.70, "shards": 4,
                         "ratio_clamped": False}},
    ])
    rec = mixed["recommendations"]["shard_count.tpu"]
    assert rec["flip"] is False and rec["value"] == 0.70

    # ...including ABOVE-parity evidence: |log 1.25| > |log 0.85|, but
    # survivors running above parity argues nothing FOR multi-shard
    # pods — a floor violation must hold the flip back in either
    # merge order
    for records in (
        [{"device": "tpu",
          "failover_ab": {"survivor_steady_ratio": 1.25, "shards": 4,
                          "ratio_clamped": False}},
         {"device": "tpu",
          "failover_ab": {"survivor_steady_ratio": 0.85, "shards": 4,
                          "ratio_clamped": False}}],
        [{"device": "tpu",
          "failover_ab": {"survivor_steady_ratio": 0.85, "shards": 4,
                          "ratio_clamped": False}},
         {"device": "tpu",
          "failover_ab": {"survivor_steady_ratio": 1.25, "shards": 4,
                          "ratio_clamped": False}}],
    ):
        rec = db.analyze(records)["recommendations"]["shard_count.tpu"]
        assert rec["flip"] is False, records
        assert rec["measured"] == 0.85


def test_bench_smoke_pallas_match():
    """`bench.py --smoke-pallas-match` — the tier-1 gate for the Pallas
    matcher kernels (config-14 A/B at seconds-scale CPU geometry, the
    pallas arm in interpret mode).  The structural claims are what
    matters: byte-identical xla/pallas trajectories and maps, one fused
    dispatch per fleet tick on both arms, zero recompiles / zero
    implicit transfers inside the timed loops (the bench itself raises
    on violation; this gate pins that the asserted artifact lands).
    Wall-time numbers are interpret-mode CI weather and double-clamped
    in the decision key; kernel-level bit-exactness lives in
    tests/test_pallas_scan_match.py."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-pallas-match"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(14)
    assert out["smoke"] is True and out["device"] == "cpu"
    # the structural claims, re-checked from the artifact
    s = out["structural"]
    assert s["one_dispatch_per_tick"] is True
    assert s["zero_recompiles"] is True
    assert s["zero_implicit_transfers"] is True
    assert s["bit_exact_parity_holds"] is True
    # both arms: one dispatch per tick (warm tick + timed ticks)
    assert out["xla"]["dispatches"] == out["ticks"] + 1
    assert out["pallas"]["dispatches"] == out["ticks"] + 1
    # accuracy + liveness
    assert 0 <= out["pose_err_cells"] <= 8.0
    assert out["value"] > 0 and out["xla"]["scans_per_sec"] > 0
    # the stage decomposition is present for both arms
    for arm in ("xla", "pallas"):
        d = out["decomposition_ms"][arm]
        assert d["match_ms"] > 0 and d["update_ms"] > 0
        assert d["refine_ms"] >= 0 and d["coarse_ms"] > 0
    # the decision key rides with BOTH clamp flags, and a CPU run is
    # always marked interpret-mode (the emulator, not the datapath)
    ab = out["pallas_match_ab"]
    assert ab["match_speedup"] > 0
    assert isinstance(ab["overhead_clamped"], bool)
    assert ab["interpret_mode"] is True
    assert "ceiling_analysis" in out


def test_decide_backends_pallas_match_key():
    """The match_backend recommendation flips from config-14 evidence
    alone: TPU Mosaic records past the bar recommend pallas; CPU
    records, clamped decompositions and interpret-mode records never
    flip (the CPU artifact is interpret-mode by construction, so it is
    doubly inert)."""
    import importlib
    import os
    import sys

    sys.modules.pop("decide_backends", None)
    scripts_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    )
    sys.path.insert(0, scripts_dir)
    try:
        db = importlib.import_module("decide_backends")
    finally:
        sys.path.remove(scripts_dir)

    out = db.analyze([
        {"device": "tpu",
         "pallas_match_ab": {"match_speedup": 2.7,
                             "overhead_clamped": False,
                             "interpret_mode": False}},
        {"device": "cpu",  # CPU record: no decision weight
         "pallas_match_ab": {"match_speedup": 9.0,
                             "overhead_clamped": False,
                             "interpret_mode": True}},
    ])
    rec = out["recommendations"]["match_backend.tpu"]
    assert rec["flip"] is True and rec["recommended"] == "pallas"
    assert rec["value"] == 2.7  # the TPU record, not the CPU 9.0
    assert out["evidence"]["pallas_match_ab"]

    # an interpret-mode record never flips, even with device=tpu (a
    # malformed record must not smuggle emulator numbers past the bar)
    interp = db.analyze([
        {"device": "tpu",
         "pallas_match_ab": {"match_speedup": 50.0,
                             "overhead_clamped": False,
                             "interpret_mode": True}},
    ])
    assert "match_backend.tpu" not in interp["recommendations"]
    assert interp["evidence"]["pallas_match_ab"]

    # a clamped decomposition records evidence but cannot flip
    clamped = db.analyze([
        {"device": "tpu",
         "pallas_match_ab": {"match_speedup": 50.0,
                             "overhead_clamped": True,
                             "interpret_mode": False}},
    ])
    assert "match_backend.tpu" not in clamped["recommendations"]

    # sub-margin TPU evidence keeps xla
    keep = db.analyze([
        {"device": "tpu",
         "pallas_match_ab": {"match_speedup": 1.02,
                             "overhead_clamped": False,
                             "interpret_mode": False}},
    ])
    rec = keep["recommendations"]["match_backend.tpu"]
    assert rec["flip"] is False and rec["recommended"] == "xla"


def test_decide_backends_mapping_key():
    """The map_backend recommendation flips from config-12 evidence
    alone: TPU records past the bar recommend fused, CPU records and
    clamped decompositions never flip."""
    import importlib
    import os
    import sys

    sys.modules.pop("decide_backends", None)
    scripts_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    )
    sys.path.insert(0, scripts_dir)
    try:
        db = importlib.import_module("decide_backends")
    finally:
        sys.path.remove(scripts_dir)

    out = db.analyze([
        {"device": "tpu",
         "mapping_ab": {"match_speedup": 4.1,
                        "per_dispatch_floor_ms": 2.0,
                        "overhead_clamped": False}},
        {"device": "cpu",  # CPU record: no decision weight
         "mapping_ab": {"match_speedup": 9.0,
                        "overhead_clamped": False}},
    ])
    rec = out["recommendations"]["map_backend.tpu"]
    assert rec["flip"] is True and rec["recommended"] == "fused"
    assert rec["value"] == 4.1  # the TPU record, not the CPU 9.0
    assert out["evidence"]["mapping_ab"]

    # a clamped decomposition records evidence but cannot flip
    clamped = db.analyze([
        {"device": "tpu",
         "mapping_ab": {"match_speedup": 50.0,
                        "overhead_clamped": True}},
    ])
    assert "map_backend.tpu" not in clamped["recommendations"]
    assert clamped["evidence"]["mapping_ab"]

    # sub-margin TPU evidence keeps host
    keep = db.analyze([
        {"device": "tpu",
         "mapping_ab": {"match_speedup": 1.01,
                        "overhead_clamped": False}},
    ])
    rec = keep["recommendations"]["map_backend.tpu"]
    assert rec["flip"] is False and rec["recommended"] == "host"


def test_decide_backends_fleet_ingest_key():
    """The fleet_ingest_backend auto mapping flips from config-10
    evidence alone: TPU records past the bar recommend fused, CPU
    records and clamped decompositions never flip."""
    import importlib
    import os
    import sys

    sys.modules.pop("decide_backends", None)
    scripts_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    )
    sys.path.insert(0, scripts_dir)
    try:
        db = importlib.import_module("decide_backends")
    finally:
        sys.path.remove(scripts_dir)

    out = db.analyze([
        {"device": "tpu",
         "fleet_ingest_ab": {"ingest_overhead_speedup": 3.2,
                             "fused_vs_host_tick_speedup": 1.4,
                             "overhead_clamped": False}},
        {"device": "cpu",  # CPU record: no decision weight
         "fleet_ingest_ab": {"ingest_overhead_speedup": 9.0,
                             "overhead_clamped": False}},
    ])
    rec = out["recommendations"]["fleet_ingest_backend.tpu"]
    assert rec["flip"] is True and rec["recommended"] == "fused"
    assert rec["value"] == 3.2  # the TPU record, not the CPU 9.0
    assert out["evidence"]["fleet_ingest_ab"]

    # a clamped decomposition records evidence but cannot flip
    clamped = db.analyze([
        {"device": "tpu",
         "fleet_ingest_ab": {"ingest_overhead_speedup": 50.0,
                             "overhead_clamped": True}},
    ])
    assert "fleet_ingest_backend.tpu" not in clamped["recommendations"]
    assert clamped["evidence"]["fleet_ingest_ab"]

    # sub-margin TPU evidence keeps host
    keep = db.analyze([
        {"device": "tpu",
         "fleet_ingest_ab": {"ingest_overhead_speedup": 1.02,
                             "overhead_clamped": False}},
    ])
    rec = keep["recommendations"]["fleet_ingest_backend.tpu"]
    assert rec["flip"] is False and rec["recommended"] == "host"


def test_bench_smoke_scenarios():
    """`bench.py --smoke-scenarios` — the tier-1 gate for the scenario
    foundry (config-23 matrix at seconds-scale CPU geometry).  The
    structural claims are what matters: scene byte-determinism across
    chunkings, the corridor tying de-skew to identity (the first-min-
    wins contract), the loop scene closing under the PR 11 machinery,
    decay-on fading a moved obstacle while decay-off stays byte-frozen,
    and the per-cell accuracy floors (the bench itself raises on
    violation; this gate pins that the asserted artifact lands).  The
    throughput headline is a catastrophe floor on CPU; the perf story
    belongs to on-chip captures."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--smoke-scenarios"],
        cwd=repo, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == bench.metric_name(23)
    assert out["smoke"] is True and out["device"] == "cpu"
    s = out["structural"]
    for claim in (
        "scene_byte_determinism_holds", "corridor_ties_deskew_to_identity",
        "loop_closes_under_pr11", "decay_fades_moved_obstacle",
        "accuracy_floors_hold",
    ):
        assert s[claim] is True, claim
    # the matrix itself: every (scene, chaos, fleet) cell carries both
    # accuracy numbers and a perf number, and the corroboration flags
    # decide_backends consumes
    cells = out["scenario_matrix"]
    assert len(cells) == len(out["scenes"]) * len(out["chaos"]) * len(
        out["fleets"]
    )
    for c in cells:
        assert c["scene"] in out["scenes"] and c["chaos"] in out["chaos"]
        assert c["end_pose_err_cells"] >= 0.0
        assert 0.0 <= c["map_f1"] <= 1.0
        assert c["scans_per_sec"] > 0
        for flag in ("deskew_ok", "loop_ok", "match_ok", "clamped"):
            assert isinstance(c[flag], bool), flag
    # the probes ride along: loop closure corrected the injected drift
    for chaos, probe in out["loop_probe"].items():
        assert probe["corrected_end_err_cells"] < probe[
            "baseline_end_err_cells"
        ], chaos
        assert probe["closures_accepted"] >= 1
    # decay: off-arm stale evidence persisted byte-frozen, on-arm faded
    dp = out["decay_probe"]
    assert dp["stale_region_max_q_off"] > 0
    assert dp["stale_region_max_q_on"] <= 0
    assert out["value"] > 0
    assert "ceiling_analysis" in out


def test_decide_backends_scenario_corroboration():
    """Config-23 cells gate accuracy-coupled flips: with scenario
    records present, a deskew/loop/match flip needs >= 2 unclamped
    supporting cells or it is downgraded to keep; clamped cells carry
    no weight; with NO scenario records the pass is inert (older
    artifact sets keep their standing semantics)."""
    import importlib
    import sys as _sys

    _sys.path.insert(0, "scripts")
    try:
        db = importlib.import_module("decide_backends")
    finally:
        _sys.path.pop(0)

    deskew = {
        "device": "tpu",
        "deskew_ab": {"update_multiplier": 2.5, "steady_tick_ratio": 0.97},
    }
    loop = {
        "device": "tpu",
        "loop_close_ab": {
            "backend_speedup": 1.3,
            "corrected_end_err_cells": 1.0,
            "steady_tick_ratio": 0.95,
        },
    }

    def cell(**flags):
        return {"scene": "x", "chaos": "clean", "clamped": False, **flags}

    # no scenario records: flips stand untouched (back-compat)
    got = db.analyze([deskew, loop])
    assert got["recommendations"]["deskew_enable.tpu"]["flip"] is True
    assert "scenario_corroboration" not in got[
        "recommendations"]["deskew_enable.tpu"]

    # >= 2 unclamped supporting cells: the flip stands, annotated
    sm2 = {"device": "tpu", "scenario_matrix": [
        cell(deskew_ok=True, loop_ok=True, match_ok=True),
        cell(deskew_ok=True, loop_ok=True, match_ok=True),
    ]}
    got = db.analyze([deskew, loop, sm2])
    for mapping in ("deskew_enable.tpu", "loop_enable.tpu",
                    "loop_backend.tpu"):
        r = got["recommendations"][mapping]
        assert r["flip"] is True and r["scenario_cells"] == 2, mapping

    # one supporting cell (the other clamped): downgraded to keep
    sm1 = {"device": "tpu", "scenario_matrix": [
        cell(deskew_ok=True, loop_ok=True),
        dict(cell(deskew_ok=True, loop_ok=True), clamped=True),
    ]}
    got = db.analyze([deskew, loop, sm1])
    for mapping, current in (("deskew_enable.tpu", "false"),
                             ("loop_enable.tpu", "false"),
                             ("loop_backend.tpu", "host")):
        r = got["recommendations"][mapping]
        assert r["flip"] is False and r["recommended"] == current, mapping
        assert "insufficient" in r["scenario_corroboration"], mapping

    # CPU scenario records: reported, no corroboration weight either way
    cpu_sm = dict(sm2, device="cpu")
    got = db.analyze([deskew, cpu_sm])
    assert got["recommendations"]["deskew_enable.tpu"]["flip"] is True
    assert "scenario_corroboration" not in got[
        "recommendations"]["deskew_enable.tpu"]
    assert got["non_tpu_ignored"]

    # scenario records WITHOUT the ratio records: cells land in
    # evidence but invent no recommendation
    got = db.analyze([sm2])
    assert "deskew_enable.tpu" not in got["recommendations"]
    assert got["evidence"]["scenario_matrix"][0]["cells"] == 2
