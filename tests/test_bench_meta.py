"""bench.py metadata invariants (no device work — safe on CPU CI).

The driver keys benchmark series by metric name; success and failure
records of one config must share a name, and no two configs may collide.
"""

import bench


def test_metric_names_unique_across_configs():
    names = {c: bench.metric_name(c) for c in bench.GRADED}
    assert len(set(names.values())) == len(names), names


def test_metric_names_stable():
    # the driver's recorded series — renames would orphan history
    assert bench.metric_name(5) == "denseboost64_filter_chain_scans_per_sec"
    assert bench.metric_name(6) == "e2e_decode_chain_scans_per_sec"
    assert bench.metric_name(1) == "a1m8_passthrough_scans_per_sec"
    assert bench.metric_name(7) == "fused_replay_scans_per_sec"
    assert bench.metric_name(4) == "graded_config4_scans_per_sec"
    assert bench.metric_name(8) == "fleet_fused_replay_scans_per_sec"


def test_graded_table_well_formed():
    for c, (kind, points, over) in bench.GRADED.items():
        assert kind in ("passthrough", "chain", "e2e", "fused", "fleet")
        assert points > 0
        assert isinstance(over, dict)
