"""Bounded pipelined/synchronous collect (collect_timeout_s).

The reference bounds every wait on the device (grab timeout 2000 ms
default, sl_lidar_driver.h:332).  This framework's analog is the
publish path's device->host fetch, which a wedged remote-attach link
can block indefinitely; with ``collect_timeout_s`` set, the fetch is
raced against a deadline and a TimeoutError surfaces to the FSM's
transient-fault path while the revolution is re-stashed for the drain.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain
from rplidar_ros2_driver_tpu.ops.filters import wire_output_len

BEAMS = 64


class _BlockingWire:
    """Stands in for a dispatched wire output whose D2H fetch stalls
    until ``release`` is set (np.asarray enters __array__)."""

    def __init__(self, release: threading.Event, payload: np.ndarray):
        self._release = release
        self._payload = payload

    def __array__(self, dtype=None, copy=None):
        self._release.wait()
        p = self._payload
        return p.astype(dtype) if dtype is not None else p


def _chain(**over) -> ScanFilterChain:
    params = DriverParams(
        filter_backend="cpu",
        filter_chain=("clip",),
        filter_window=2,
        voxel_grid_size=8,
        pipelined_publish=True,
        **over,
    )
    return ScanFilterChain(params, beams=BEAMS, warmup=False)


def _payload(chain: ScanFilterChain) -> np.ndarray:
    return np.zeros(wire_output_len(chain.cfg), np.float32)


def test_flush_times_out_restashes_and_recovers():
    chain = _chain(collect_timeout_s=0.2)
    release = threading.Event()
    chain._pending_wire = _BlockingWire(release, _payload(chain))
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        chain.flush_pipelined()
    assert time.monotonic() - t0 < 5.0  # bounded, not wedged
    # the revolution is re-stashed so a later drain can retry ...
    assert chain._pending_wire is not None
    # ... and once the link resolves, the retry publishes it
    release.set()
    out = chain.flush_pipelined()
    assert out is not None
    assert chain._pending_wire is None


def test_streaming_collect_times_out_and_restashes():
    chain = _chain(collect_timeout_s=0.2)
    release = threading.Event()
    chain._pending_wire = _BlockingWire(release, _payload(chain))
    rng = np.random.default_rng(0)
    angle = (rng.uniform(0, 1 << 14, 200)).astype(np.uint16)
    dist = (rng.uniform(400, 4000, 200)).astype(np.uint16)
    qual = np.full(200, 47, np.uint8)
    with pytest.raises(TimeoutError):
        chain.process_raw_pipelined(angle, dist, qual)
    # popped-but-unpublished revolution went back for the drain
    assert isinstance(chain._pending_wire, _BlockingWire)
    release.set()
    assert chain.flush_pipelined() is not None


def test_timeout_zero_or_none_is_unbounded():
    # None (default) and 0 both mean "no deadline": the fetch runs
    # inline on the calling thread (no helper thread involved)
    from rplidar_ros2_driver_tpu.utils.fetch import bounded_fetch

    for v in (None, 0):
        assert bounded_fetch(threading.get_ident, v) == threading.get_ident()
        chain = _chain(collect_timeout_s=v)
        release = threading.Event()
        release.set()  # never blocks
        chain._pending_wire = _BlockingWire(release, _payload(chain))
        assert chain.flush_pipelined() is not None


def test_node_drain_discards_on_timeout():
    # the node's drain policy is drop-not-retry: after a timed-out drain
    # the chain must hold no orphaned wire (node/node.py discards it)
    chain = _chain(collect_timeout_s=0.2)
    release = threading.Event()
    chain._pending_wire = _BlockingWire(release, _payload(chain))
    with pytest.raises(TimeoutError):
        chain.flush_pipelined()
    assert chain._pending_wire is not None  # re-stashed by flush ...
    chain.discard_pipelined()  # ... and explicitly dropped by the node
    assert chain._pending_wire is None
    release.set()
    assert chain.flush_pipelined() is None


def test_epoch_guard_still_wins_over_restash():
    # a reset between pop and re-stash must keep the pre-reset output
    # dropped (restore-race invariant, unchanged by the timeout path)
    chain = _chain(collect_timeout_s=0.2)
    release = threading.Event()
    chain._pending_wire = _BlockingWire(release, _payload(chain))
    with pytest.raises(TimeoutError):
        chain.flush_pipelined()
    chain.reset()  # epoch moves; pending cleared
    assert chain._pending_wire is None
    release.set()
    assert chain.flush_pipelined() is None


def test_service_tick_collect_times_out_and_restashes():
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
    from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh

    params = DriverParams(
        filter_backend="cpu",
        filter_chain=("clip",),
        filter_window=2,
        voxel_grid_size=8,
        collect_timeout_s=0.2,
    )
    svc = ShardedFilterService(
        params, streams=2, mesh=make_mesh(8), beams=BEAMS, capacity=256
    )
    release = threading.Event()
    n = svc.streams

    def blocked(out, live):  # instance attr: called unbound as (out, live)
        release.wait()
        return [object()] * n

    svc._blocked = blocked
    svc._pending = (None, [True] * n, "_blocked")
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        svc.flush_pipelined()
    assert time.monotonic() - t0 < 5.0
    assert svc._pending is not None  # re-stashed for a later drain
    release.set()
    assert svc.flush_pipelined() is not None


def test_fsm_recovers_from_wedged_collect():
    """Full node path: a wedged device->host fetch mid-stream must trip
    collect_timeout_s, surface as a transient fault, drive the FSM
    through RESETTING, and — once the link resolves — resume publishing.
    This is the behavior the reference's bounded grab buys its FSM
    (src/rplidar_node.cpp:417-448), reproduced at this framework's
    publish seam."""
    from rplidar_ros2_driver_tpu.driver.dummy import DummyLidarDriver
    from rplidar_ros2_driver_tpu.node.fsm import FsmTimings
    from rplidar_ros2_driver_tpu.node.node import CollectingPublisher, RPlidarNode
    from rplidar_ros2_driver_tpu.ops.filters import unpack_output_wire
    from rplidar_ros2_driver_tpu.utils.fetch import bounded_fetch

    params = DriverParams(
        dummy_mode=True,
        max_retries=2,
        filter_backend="cpu",
        filter_chain=("clip",),
        filter_window=2,
        voxel_grid_size=8,
        pipelined_publish=True,
        collect_timeout_s=0.15,
    )
    pub = CollectingPublisher()
    node = RPlidarNode(
        params, pub,
        driver_factory=lambda: DummyLidarDriver(scan_rate_hz=200.0),
        fsm_timings=FsmTimings.fast(),
    )
    wedge = threading.Event()

    def deadline():
        return time.monotonic() + 20.0

    from rplidar_ros2_driver_tpu.node.node import launch

    launch(node)
    try:
        chain = node.chain

        def wedgeable_collect(wire):
            def fetch():
                while wedge.is_set():  # the "link": blocked while wedged
                    time.sleep(0.01)
                return unpack_output_wire(wire, chain.cfg)

            return bounded_fetch(fetch, chain.collect_timeout_s, "test fetch")

        chain._collect = wedgeable_collect

        t_end = deadline()
        while pub.scan_count < 3 and time.monotonic() < t_end:
            time.sleep(0.01)
        assert pub.scan_count >= 3  # streaming before the wedge

        wedge.set()
        t_end = deadline()
        while node.fsm.reset_count < 1 and time.monotonic() < t_end:
            time.sleep(0.01)
        assert node.fsm.reset_count >= 1  # bounded fault -> FSM recovery

        wedge.clear()
        before = pub.scan_count
        t_end = deadline()
        while pub.scan_count < before + 3 and time.monotonic() < t_end:
            time.sleep(0.01)
        assert pub.scan_count >= before + 3  # stream resumed after the wedge
    finally:
        wedge.clear()
        node.shutdown()


def test_collect_timeout_validation():
    with pytest.raises(ValueError):
        DriverParams(collect_timeout_s=-1.0).validate()
    DriverParams(collect_timeout_s=2.0).validate()
    DriverParams(collect_timeout_s=None).validate()
