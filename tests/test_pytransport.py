"""Pure-Python transport fallback (protocol/pytransport.py).

The same end-to-end drives test_real_driver.py runs over the native C++
transport, run over PyChannel/PyTransceiver instead: the fallback must be
behaviorally identical (connect, mode start, streaming, hot-unplug), not
just importable.
"""

import time
from unittest import mock


from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import (
    SerialSimulatedDevice,
    SimulatedDevice,
)
from rplidar_ros2_driver_tpu.protocol.pytransport import PyChannel, PyTransceiver


def _py_factory(channel_type, port, baudrate, host, net_port):
    if channel_type == "serial":
        ch = PyChannel("serial", port, baud=baudrate)
    elif channel_type == "tcp":
        ch = PyChannel("tcp", host, port=net_port)
    else:
        ch = PyChannel("udp", host, port=net_port)
    return PyTransceiver(ch)


class TestPyTcp:
    def test_connect_stream_unplug(self):
        sim = SimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0, transceiver_factory=_py_factory,
            )
            assert drv.connect("sim", 0, False)
            assert drv.device_info is not None
            drv.detect_and_init_strategy()
            assert drv.start_motor("DenseBoost", 600)
            got = None
            deadline = time.monotonic() + 15
            while got is None and time.monotonic() < deadline:
                got = drv.grab_scan_host(2.0)
            assert got is not None
            scan, ts0, dur = got
            assert len(scan["angle_q14"]) > 100
            assert dur > 0
            # hot-unplug: the rx thread must surface the dead link
            sim.unplug()
            t0 = time.monotonic()
            while drv.grab_scan_host(0.5) is not None:
                assert time.monotonic() - t0 < 10
            assert not drv._engine.healthy
            drv.disconnect()
        finally:
            sim.stop()

    def test_conf_protocol_round_trips(self):
        """Request/response (non-loop) answers flow through the same
        decoder: health + scan-mode enumeration over the fallback."""
        sim = SimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0, transceiver_factory=_py_factory,
            )
            assert drv.connect("sim", 0, False)
            assert drv.get_health() is not None
            drv.detect_and_init_strategy()
            assert drv.start_motor("DenseBoost", 600)
            assert any(m.name == "DenseBoost" for m in drv.scan_modes)
            drv.stop_motor()
            drv.disconnect()
        finally:
            sim.stop()


class TestPySerial:
    def test_serial_pty_stream(self):
        """termios2 BOTHER + raw-8N1 against the pty emulator."""
        sim = SerialSimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="serial", motor_warmup_s=0.0,
                transceiver_factory=_py_factory,
            )
            assert drv.connect(sim.port_path, 115200, True)
            drv.detect_and_init_strategy()
            assert drv.start_motor("", 600)
            got = None
            deadline = time.monotonic() + 15
            while got is None and time.monotonic() < deadline:
                got = drv.grab_scan_host(2.0)
            assert got is not None
            assert len(got[0]["angle_q14"]) > 0
            sim.unplug()
            t0 = time.monotonic()
            while drv.grab_scan_host(0.5) is not None:
                assert time.monotonic() - t0 < 10
            drv.disconnect()
        finally:
            sim.stop()


class TestPyUdp:
    def test_udp_connect_stream_silence(self):
        """Connected-pair UDP datagrams through the Python channel; an
        unplugged radio is silence (datagrams just stop), not an error."""
        from rplidar_ros2_driver_tpu.driver.sim_device import UdpSimulatedDevice

        sim = UdpSimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="udp", udp_host="127.0.0.1", udp_port=sim.port,
                motor_warmup_s=0.0, transceiver_factory=_py_factory,
            )
            assert drv.connect("udp", 0, True)
            drv.detect_and_init_strategy()
            assert drv.start_motor("", 600)
            got = None
            deadline = time.monotonic() + 15
            while got is None and time.monotonic() < deadline:
                got = drv.grab_scan_host(2.0)
            assert got is not None
            assert len(got[0]["angle_q14"]) > 0
            assert not drv._scan_decoder.timing.is_serial
            sim.unplug()
            t0 = time.monotonic()
            while drv.grab_scan_host(0.5) is not None:
                assert time.monotonic() - t0 < 10
            drv.disconnect()
        finally:
            sim.stop()


class TestFallbackSelection:
    def test_factory_falls_back_when_native_unavailable(self):
        """_default_transceiver_factory must hand out the Python transport
        when the native library cannot load (and only then)."""
        from rplidar_ros2_driver_tpu.driver.real import _default_transceiver_factory
        from rplidar_ros2_driver_tpu.native import NativeUnavailable

        with mock.patch(
            "rplidar_ros2_driver_tpu.native.runtime.load",
            side_effect=NativeUnavailable("forced by test"),
        ):
            tx = _default_transceiver_factory("tcp", "", 0, "127.0.0.1", 1)
            assert isinstance(tx, PyTransceiver)

    def test_channel_errors_are_the_engines_class(self):
        """The pump catches native.runtime.ChannelError; the fallback must
        raise exactly that class."""
        from rplidar_ros2_driver_tpu.native.runtime import ChannelError
        from rplidar_ros2_driver_tpu.protocol import pytransport

        assert pytransport.ChannelError is ChannelError

    def test_cancel_unblocks_parked_reader(self):
        """close/cancel must unblock a reader parked in select (self-pipe)."""
        import socket as socketmod
        import threading

        srv = socketmod.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        ch = PyChannel("tcp", "127.0.0.1", port=srv.getsockname()[1])
        assert ch.open()
        srv.accept()
        out = {}

        def reader():
            out["r"] = ch.read(64, timeout_ms=10_000)

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.2)
        ch.cancel()
        t.join(2.0)
        assert not t.is_alive()
        assert out["r"] == b""
        ch.close()
        srv.close()
