"""Motor-control strategy, motor info, MAC/IP conf, and autobaud negotiation.

Covers the 3-way motor dispatch (checkMotorCtrlSupport / setMotorSpeed,
sl_lidar_driver.cpp:833-878, 968-1021), getMotorInfo (:1023-1056), the
MAC / static-IP conf keys (:887-955), and negotiateSerialBaudRate
(:1058-1155) against a raw fake serial channel.
"""

import struct
import time

import pytest

from rplidar_ros2_driver_tpu import native as native_mod
from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import SimConfig, SimulatedDevice
from rplidar_ros2_driver_tpu.models.tables import MotorCtrlSupport
from rplidar_ros2_driver_tpu.protocol.conf import IpConf
from rplidar_ros2_driver_tpu.protocol.constants import (
    AUTOBAUD_MAGICBYTE,
    Cmd,
)

pytestmark = pytest.mark.skipif(
    not native_mod.available(), reason="native library unavailable"
)


def make_driver(sim: SimulatedDevice) -> RealLidarDriver:
    return RealLidarDriver(
        channel_type="tcp",
        tcp_host=SimulatedDevice.TARGET,
        tcp_port=sim.port,
        motor_warmup_s=0.0,
        legacy_warmup_s=0.0,
    )


def connected(cfg=None):
    dev = SimulatedDevice(cfg or SimConfig()).start()
    drv = make_driver(dev)
    assert drv.connect("ignored", 0, True)
    return dev, drv


class TestMotorCtrlSupport:
    def test_s_series_builtin_rpm(self):
        dev, drv = connected(SimConfig(model_id=0x71))  # major 7 >= 6
        try:
            assert drv.motor_ctrl is MotorCtrlSupport.RPM
        finally:
            drv.disconnect(); dev.stop()

    def test_a2_with_acc_board_is_pwm(self):
        dev, drv = connected(SimConfig(model_id=0x28, acc_board_pwm=True))
        try:
            assert drv.motor_ctrl is MotorCtrlSupport.PWM
            assert drv.set_motor_speed(660)
            time.sleep(0.2)
            assert Cmd.SET_MOTOR_PWM in dev.commands
        finally:
            drv.disconnect(); dev.stop()

    def test_a2_without_acc_board_is_none(self):
        dev, drv = connected(SimConfig(model_id=0x28, acc_board_pwm=False))
        try:
            assert drv.motor_ctrl is MotorCtrlSupport.NONE
        finally:
            drv.disconnect(); dev.stop()

    def test_a1_is_none_without_probe(self):
        dev, drv = connected(SimConfig(model_id=0x18))  # major 1 < 2
        try:
            assert drv.motor_ctrl is MotorCtrlSupport.NONE
            # the acc-board probe must not even be sent for major id < 2
            assert Cmd.GET_ACC_BOARD_FLAG not in dev.commands
        finally:
            drv.disconnect(); dev.stop()

    def test_default_speed_queries_desired(self):
        dev, drv = connected(SimConfig(model_id=0x71, desired_rpm=720))
        try:
            assert drv.set_motor_speed(None)
            assert _wait(lambda: dev.motor_rpm == 720)
        finally:
            drv.disconnect(); dev.stop()


class TestConfSupportGate:
    """checkSupportConfigCommands semantics (sl_lidar_driver.cpp:1176-1196):
    a device whose firmware predates the conf protocol must never be sent
    a GET/SET_LIDAR_CONF query — each one would silently time out."""

    def test_pre_conf_device_never_queried(self):
        # A2 with acc-board PWM: the PWM motor path would otherwise fetch
        # DESIRED_ROT_FREQ on set_motor_speed(None)
        dev, drv = connected(SimConfig(
            model_id=0x28, firmware=0x0117, acc_board_pwm=True,
        ))
        try:
            assert not drv.conf_supported
            assert drv.get_motor_info() is None
            assert drv.get_mac_addr() is None
            assert drv.get_ip_conf() is None
            assert not drv.set_ip_conf(IpConf(
                (192, 168, 0, 7), (255, 255, 255, 0), (192, 168, 0, 1)
            ))
            assert drv.set_motor_speed(None)  # falls back to 600 default
            assert _wait(lambda: dev.motor_rpm == 600)
            assert Cmd.GET_LIDAR_CONF not in dev.commands
            assert Cmd.SET_LIDAR_CONF not in dev.commands
        finally:
            drv.disconnect(); dev.stop()

    def test_firmware_1_24_boundary_enables_conf(self):
        # exactly 1.24 on a triangle unit: the boundary itself qualifies —
        # pins the `>=` comparison direction
        dev, drv = connected(SimConfig(
            model_id=0x28, firmware=(0x1 << 8) | 24, acc_board_pwm=True,
        ))
        try:
            assert drv.conf_supported
            info = drv.get_motor_info()
            assert info is not None and info.max_speed == 1200
        finally:
            drv.disconnect(); dev.stop()


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


class TestMotorInfoAndNetworkConf:
    def test_get_motor_info(self):
        dev, drv = connected(SimConfig(min_rpm=180, max_rpm=1100, desired_rpm=650))
        try:
            info = drv.get_motor_info()
            assert info is not None
            assert (info.min_speed, info.max_speed, info.desired_speed) == (180, 1100, 650)
        finally:
            drv.disconnect(); dev.stop()

    def test_mac_addr(self):
        dev, drv = connected()
        try:
            assert drv.get_mac_addr() == b"\xaa\xbb\xcc\xdd\xee\xff"
        finally:
            drv.disconnect(); dev.stop()

    def test_ip_conf_roundtrip(self):
        dev, drv = connected()
        try:
            conf = drv.get_ip_conf()
            assert conf is not None and conf.ip == (192, 168, 11, 2)
            new = IpConf((10, 0, 0, 5), (255, 255, 0, 0), (10, 0, 0, 1))
            assert drv.set_ip_conf(new)
            assert drv.get_ip_conf() == new
        finally:
            drv.disconnect(); dev.stop()


# ---------------------------------------------------------------------------
# autobaud against a fake raw serial channel
# ---------------------------------------------------------------------------


class FakeSerialChannel:
    """Raw-channel fake emulating device-side baud measurement firmware."""

    kind = "serial"

    def __init__(self, detected_baud=460800, magic_threshold=32):
        self.detected_baud = detected_baud
        self.magic_threshold = magic_threshold
        self._magic_seen = 0
        self._reply = b""
        self.opened = False
        self.writes = []

    def open(self):
        self.opened = True
        return True

    def close(self):
        self.opened = False

    def write(self, data: bytes) -> int:
        self.writes.append(bytes(data))
        n_magic = sum(1 for b in data if b == AUTOBAUD_MAGICBYTE)
        self._magic_seen += n_magic
        if self._magic_seen >= self.magic_threshold and not self._reply:
            self._reply = struct.pack("<I", self.detected_baud)
        return len(data)

    def read(self, max_bytes: int, timeout_ms: int = 0):
        if not self._reply:
            return None  # timeout
        out, self._reply = self._reply[:max_bytes], self._reply[max_bytes:]
        return out

    def set_dtr(self, level):
        return True


from conftest import ScriptedTransceiver as FakeTransceiver  # noqa: E402


def test_autobaud_negotiation_flow():
    ch = FakeSerialChannel(detected_baud=256000)
    tx = FakeTransceiver(ch)
    drv = RealLidarDriver(transceiver_factory=lambda *a, **k: tx)
    # hand-wire a started engine (connect() would need a devinfo answer)
    from rplidar_ros2_driver_tpu.protocol.engine import CommandEngine

    drv._engine = CommandEngine(tx)
    assert drv._engine.start()
    drv._connected = True

    detected = drv.negotiate_serial_baud(256000)
    assert detected == 256000
    # confirmation packet went out with flag 0x5F5F + required bps
    confirm = [p for p in tx.sent if len(p) > 2 and p[1] == Cmd.NEW_BAUDRATE_CONFIRM]
    assert confirm, f"no NEW_BAUDRATE_CONFIRM among {tx.sent!r}"
    payload = confirm[-1][3:-1]  # strip A5 cmd size ... checksum
    flag, bps, _ = struct.unpack("<HIH", payload)
    assert flag == 0x5F5F and bps == 256000
    # transceiver restarted after raw-mode negotiation
    assert tx.running
    drv._engine.stop()


def test_autobaud_mismatch_not_confirmed():
    """A detected rate != required must NOT be confirmed: confirming would
    switch the device's UART away from the link the host keeps using."""
    ch = FakeSerialChannel(detected_baud=115200)
    tx = FakeTransceiver(ch)
    drv = RealLidarDriver(transceiver_factory=lambda *a, **k: tx)
    from rplidar_ros2_driver_tpu.protocol.engine import CommandEngine

    drv._engine = CommandEngine(tx)
    assert drv._engine.start()
    drv._connected = True

    detected = drv.negotiate_serial_baud(256000)
    assert detected == 115200  # measurement still reported to the caller
    confirm = [p for p in tx.sent if len(p) > 2 and p[1] == Cmd.NEW_BAUDRATE_CONFIRM]
    assert not confirm, "mismatched baud must not be confirmed"
    assert tx.running
    drv._engine.stop()


def test_autobaud_rejected_on_non_serial():
    class TcpChannel(FakeSerialChannel):
        kind = "tcp"

    tx = FakeTransceiver(TcpChannel())
    drv = RealLidarDriver(transceiver_factory=lambda *a, **k: tx)
    from rplidar_ros2_driver_tpu.protocol.engine import CommandEngine

    drv._engine = CommandEngine(tx)
    assert drv._engine.start()
    drv._connected = True
    assert drv.negotiate_serial_baud(256000) is None
    drv._engine.stop()


def test_supports_conf_commands_boundaries():
    """Table-level pin of the gate the driver tests above exercise
    end-to-end: ND magic starts at major id 4, triangle firmware at
    exactly 1.24 (sl_lidar_driver.cpp:1176-1196, 1467-1470)."""
    from rplidar_ros2_driver_tpu.models.tables import (
        DeviceInfo,
        supports_conf_commands,
    )

    assert supports_conf_commands(DeviceInfo(model=0x40, firmware_version=0))
    assert not supports_conf_commands(DeviceInfo(model=0x3F, firmware_version=0))
    assert supports_conf_commands(
        DeviceInfo(model=0x18, firmware_version=(1 << 8) | 24)
    )
    assert not supports_conf_commands(
        DeviceInfo(model=0x18, firmware_version=(1 << 8) | 23)
    )
