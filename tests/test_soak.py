"""Full-stack soak: sustained streaming at a multiple of device pace.

The reference's only stress protocol is manual (README "Call for
Experiments": spin it up and watch).  This automates it: the simulator
streams DenseBoost wire frames faster than any real S2 spins, through
the real stack (native/pure-Python channel -> engine pump -> batched
decode -> assembly -> grab), and the test asserts the consumer keeps up
— throughput tracks the device pace and the newest-wins double buffer
drops stay bounded (drops mean the consumer lagged a full revolution,
sl_lidar_driver.cpp:302-305 semantics).
"""

import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import (
    SerialSimulatedDevice,
    SimConfig,
    SimulatedDevice,
)


from test_pytransport import _py_factory  # shared TCP fallback factory


@pytest.mark.parametrize(
    "rate_mult,transport",
    [
        (1.0, "native"),
        (3.0, "native"),
        (1.0, "python"),
        # serial plane: the same DenseBoost cadence through a pty via the
        # termios2/select path in native/src/channel.cc — the reference's
        # production transport (arch/linux/net_serial.cpp:300-386) must
        # hold the highest sustained rate too, not just round-trip tests
        (1.0, "serial"),
        (3.0, "serial"),
    ],
)
def test_sustained_stream_keeps_up(rate_mult, transport):
    """At device pace and at 3x device pace the grab loop must see
    (nearly) every revolution: decode + assembly are not the bottleneck.
    The pure-Python transport fallback must also hold device pace."""
    # DenseBoost cadence: 3200 pts/rev @ 10 rev/s = 800 frames/s (64
    # nodes/ultra-dense pair frame -> 50 frames/rev)
    frame_rate = 800.0 * rate_mult
    cfg = SimConfig(points_per_rev=3200, frame_rate_hz=frame_rate)
    serial = transport == "serial"
    sim = (SerialSimulatedDevice(cfg) if serial else SimulatedDevice(cfg)).start()
    seconds = 4.0
    try:
        if serial:
            drv = RealLidarDriver(channel_type="serial", motor_warmup_s=0.0)
            assert drv.connect(sim.port_path, 115200, False)
        else:
            drv = RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0,
                transceiver_factory=_py_factory if transport == "python" else None,
            )
            assert drv.connect("sim", 0, False)
        drv.detect_and_init_strategy()
        assert drv.start_motor("DenseBoost", 600)

        grabbed = 0
        durations = []
        backlogs = []
        t_end = time.monotonic() + seconds
        while time.monotonic() < t_end:
            got = drv.grab_scan_host(2.0)
            # kernel queue probe: SIOCOUTQ on the TCP connection socket,
            # FIONREAD on the pty slave input queue for serial — both
            # report "bytes the consumer hasn't drained"
            backlogs.append(sim.tx_backlog_bytes())
            if got is None:
                continue
            scan, ts0, duration = got
            grabbed += 1
            durations.append(duration)
            assert 2500 <= len(scan["angle_q14"]) <= 4000
        asm = drv._assembler
        completed, dropped = asm.scans_completed, asm.scans_dropped
        decoded = drv._scan_decoder.nodes_decoded
        emitted = sim.points_emitted
        stalls = sim.stream_send_stalls
        span = time.monotonic() - sim.stream_t0
        drv.stop_motor()
        drv.disconnect()
    finally:
        sim.stop()

    # "keeping up" means tracking what the device actually produced —
    # under CI load the sim's own pacer can run below nominal rate, so
    # the yardstick is delivered points, not wall-clock * nominal rate.
    # That alone would be self-referential (TCP backpressure couples the
    # sim's pace to the consumer's reads), so two timing-insensitive
    # backpressure signals discriminate "consumer can't keep up" from
    # "CI host is slow": (1) hard send stalls (>100 ms blocked in send —
    # a fully parked consumer), (2) kernel TX queue occupancy sampled
    # every grab (a merely-slow consumer pins the socket buffer full;
    # a starved sim thread leaves it near empty).
    # coarse secondary signal only: a >100 ms _send can also be the sim
    # thread descheduled under extreme CI load, so the bound sits above
    # anything scheduling jitter produces; a fully parked consumer hits
    # ~duration/0.5 s stalls AND pins the TX queue (the primary signal)
    assert stalls <= 8, (stalls, span)
    if backlogs:
        med_backlog = float(np.median(backlogs))
        # the pty input queue is 4096 bytes (a parked consumer pins it at
        # 4095 with ZERO stalls — small writes block too briefly to trip
        # the 100 ms stall counter, so this is the primary serial signal);
        # TCP socket buffers are tens of KB, hence the larger bound
        limit = 2048 if serial else 64 * 1024
        assert med_backlog <= limit, (med_backlog, max(backlogs))
    produced_revs = emitted / 3200.0
    assert produced_revs >= 0.4 * seconds * 10.0 * rate_mult, produced_revs
    # the consumer must see at least ~70% of revolutions produced (slack
    # for startup, CI scheduling jitter, and the final partial rev)
    assert grabbed >= 0.7 * produced_revs - 2, (grabbed, produced_revs)
    # newest-wins drops bounded: lagging a revolution now and then is
    # legal, persistent lag is the failure this test exists to catch
    assert dropped <= 0.2 * completed + 2, (dropped, completed)
    # decode throughput actually sustained the elevated sample rate.
    # This is the slow-decoder detector: the rx thread drains the socket
    # unconditionally (drop-oldest queue, transceiver.cc kMaxQueued), so
    # a decode bottleneck cannot throttle the sim's pace — it surfaces
    # as dropped frames, i.e. decoded falling behind emitted.
    assert decoded >= 0.7 * emitted - 3200
    # revolution durations track the actual production pace (mean vs
    # mean: the sim-side stream span divided by revolutions delivered)
    mean_dur = float(np.mean(durations))
    actual_period = span / max(produced_revs, 1e-9)
    assert mean_dur == pytest.approx(actual_period, rel=0.35), (
        mean_dur,
        actual_period,
    )
