"""SLAM front-end suite (mapping/mapper + ops/scan_match).

The contracts under test:

  * GOLDEN — the host-reference matcher recovers known synthetic pose
    offsets (translation and rotation) to lattice resolution on a
    synthetic room.
  * PARITY — the fused vmapped fleet lowering is BIT-EXACT against N
    independent host-reference steps (fleet sizes 1/3/8, both voxel
    kernel lowerings) — not "close", byte-equal.
  * ROBUSTNESS — degenerate scans (all-invalid, single-beam) and idle
    streams never corrupt the map or the pose.
  * CHECKPOINT — snapshot/restore mid-run resumes bit-exactly, the
    versioned schema rejects mismatches, and the node-level combined
    checkpoint (chain + ``mapper.*`` keys) round-trips through disk.
"""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.mapping.mapper import (
    FleetMapper,
    map_config_from_params,
)
from rplidar_ros2_driver_tpu.ops.scan_match import (
    SUB,
    MapConfig,
    min_quant_shift,
    rotation_table,
)

BEAMS = 256


def _params(**kw) -> DriverParams:
    base = dict(
        dummy_mode=True,
        filter_backend="cpu",
        filter_chain=("clip", "median", "voxel"),
        map_enable=True,
        map_backend="host",
        map_grid=64,
        map_cell_m=0.1,
    )
    base.update(kw)
    return DriverParams(**base)


def _room_points(pose_xyt, n: int = BEAMS, half: float = 2.5):
    """A 5x5 m square room observed from ``pose_xyt``: n beam rays cast
    to the walls, returned in the sensor frame (f32 points + mask)."""
    t = np.linspace(0, 2 * np.pi, n, endpoint=False)
    dx, dy = np.cos(t), np.sin(t)
    with np.errstate(divide="ignore"):
        r = np.minimum(
            np.where(np.abs(dx) > 1e-12, half / np.abs(dx), np.inf),
            np.where(np.abs(dy) > 1e-12, half / np.abs(dy), np.inf),
        )
    wx, wy = dx * r, dy * r
    x0, y0, th = pose_xyt
    c, s = np.cos(-th), np.sin(-th)
    px = c * (wx - x0) - s * (wy - y0)
    py = s * (wx - x0) + c * (wy - y0)
    return np.stack([px, py], 1).astype(np.float32), np.ones(n, bool)


def _submit_one(mapper: FleetMapper, pts, mask):
    return mapper.submit_points(
        pts[None], mask[None], np.ones((1,), np.int32)
    )[0]


# ---------------------------------------------------------------------------
# config / params
# ---------------------------------------------------------------------------


class TestConfig:
    def test_quant_shift_bound(self):
        for clamp_q, beams in ((8192, 2048), (8192, 256), (16384, 4096)):
            s = min_quant_shift(clamp_q, beams)
            assert (clamp_q >> s) * SUB * SUB * beams < 2**31
            if s > 0:  # minimality: one less shift would overflow
                assert (clamp_q >> (s - 1)) * SUB * SUB * beams >= 2**31

    def test_config_rejects_overflowing_score(self):
        with pytest.raises(ValueError, match="int32"):
            MapConfig(beams=4096, clamp_q=16384, quant_shift=0)

    def test_config_rejects_bad_geometry(self):
        with pytest.raises(ValueError):
            MapConfig(grid=60, coarse=8)  # not divisible
        with pytest.raises(ValueError):
            MapConfig(coarse=3)  # not a power of two
        with pytest.raises(ValueError):
            MapConfig(cell_m=0.0)

    def test_param_validation(self):
        def validate(**kw):
            # direct construction skips validation (the node / from_dict
            # call it); exercise the validator explicitly
            _params(**kw).validate()

        validate()  # the baseline params are sane
        with pytest.raises(ValueError, match="map_backend"):
            validate(map_backend="gpu")
        with pytest.raises(ValueError, match="filter_chain"):
            DriverParams(map_enable=True).validate()  # mapper needs the chain
        with pytest.raises(ValueError, match="map_grid"):
            validate(map_grid=6)
        with pytest.raises(ValueError, match="map_grid"):
            validate(map_grid=258)  # not a multiple of 4
        with pytest.raises(ValueError, match="map_cell_m"):
            validate(map_cell_m=-0.1)
        with pytest.raises(ValueError, match="map_match_window"):
            validate(map_match_window=0.0)
        with pytest.raises(ValueError, match="map_log_odds_hit"):
            validate(map_log_odds_hit=-0.5)
        with pytest.raises(ValueError, match="map_log_odds_miss"):
            validate(map_log_odds_miss=0.2)
        with pytest.raises(ValueError, match="map_log_odds_clamp"):
            validate(map_log_odds_clamp=0.1, map_log_odds_hit=0.9)

    def test_config_from_params_window(self):
        cfg = map_config_from_params(_params(map_match_window=0.8), BEAMS)
        # 0.8 m at 0.1 m/cell, coarse 4 -> 2 coarse cells
        assert cfg.window_cells == 2
        assert cfg.hit_q == 922 and cfg.miss_q == -410

    def test_rotation_table_anchors(self):
        t = rotation_table(720)
        assert t.shape == (720, 2)
        assert t[0, 0] == 1 << 14 and t[0, 1] == 0        # cos 0, sin 0
        assert t[180, 0] == 0 and t[180, 1] == 1 << 14    # 90 deg


# ---------------------------------------------------------------------------
# golden: known offsets recovered to lattice resolution
# ---------------------------------------------------------------------------


class TestGolden:
    def test_empty_map_yields_identity(self):
        mapper = FleetMapper(_params(), 1, beams=BEAMS)
        pts, m = _room_points((0, 0, 0))
        est = _submit_one(mapper, pts, m)
        assert est.score == 0  # nothing to match against yet
        assert tuple(est.pose_q) == (0, 0, 0)
        assert est.revision == 1 and est.matched_points == BEAMS

    @pytest.mark.parametrize("offset_cells", [(2, -1), (-3, 2), (0, 4)])
    def test_translation_recovered_to_lattice(self, offset_cells):
        mapper = FleetMapper(_params(), 1, beams=BEAMS)
        cfg = mapper.cfg
        pts, m = _room_points((0, 0, 0))
        _submit_one(mapper, pts, m)  # seed the map at the origin
        dx = offset_cells[0] * cfg.cell_m
        dy = offset_cells[1] * cfg.cell_m
        pts2, m2 = _room_points((dx, dy, 0.0))
        est = _submit_one(mapper, pts2, m2)
        assert est.score > 0
        # recovered to the fine lattice pitch (one cell)
        assert abs(est.pose_q[0] / SUB - offset_cells[0]) <= 1
        assert abs(est.pose_q[1] / SUB - offset_cells[1]) <= 1

    @pytest.mark.parametrize("theta_steps", [2, -3, 5])
    def test_rotation_recovered_to_lattice(self, theta_steps):
        mapper = FleetMapper(_params(), 1, beams=BEAMS)
        cfg = mapper.cfg
        step = 2 * np.pi / cfg.theta_divisions
        pts, m = _room_points((0, 0, 0))
        _submit_one(mapper, pts, m)
        pts2, m2 = _room_points((0, 0, theta_steps * step))
        est = _submit_one(mapper, pts2, m2)
        assert est.score > 0
        got = int(est.pose_q[2])
        if got > cfg.theta_divisions // 2:
            got -= cfg.theta_divisions
        assert abs(got - theta_steps) <= 1

    def test_drift_tracked_over_sequence(self):
        mapper = FleetMapper(_params(), 1, beams=BEAMS)
        cfg = mapper.cfg
        step = 2 * np.pi / cfg.theta_divisions
        true = None
        for k in range(8):
            true = (0.05 * k, -0.03 * k, 2 * k * step)
            pts, m = _room_points(true)
            est = _submit_one(mapper, pts, m)
        assert abs(est.x_m - true[0]) <= 2 * cfg.cell_m
        assert abs(est.y_m - true[1]) <= 2 * cfg.cell_m
        assert abs(est.theta_rad - true[2]) <= 2 * step


# ---------------------------------------------------------------------------
# parity: fused (vmapped) vs host reference, bit-exact
# ---------------------------------------------------------------------------


def _fleet_inputs(streams: int, tick: int, beams: int = BEAMS):
    """Per-tick fleet inputs with per-stream pose drift and a rotating
    idle pattern (every stream skips some ticks)."""
    pts = np.zeros((streams, beams, 2), np.float32)
    masks = np.zeros((streams, beams), bool)
    live = np.zeros((streams,), np.int32)
    for s in range(streams):
        if (tick + s) % 4 == 3:
            continue  # idle this tick
        pose = (0.04 * tick * (1 + 0.3 * s), -0.03 * tick, 0.003 * tick)
        p, m = _room_points(pose, beams)
        # per-stream beam dropouts so masks differ across the fleet
        rng = np.random.default_rng(100 * s + tick)
        m &= rng.uniform(size=beams) > 0.1
        pts[s], masks[s] = p, m
        live[s] = 1
    return pts, masks, live


class TestParity:
    @pytest.mark.parametrize("streams", [1, 3, 8])
    def test_fused_bit_exact_vs_host(self, streams):
        host = FleetMapper(_params(), streams, beams=BEAMS)
        fused = FleetMapper(
            _params(map_backend="fused"), streams, beams=BEAMS
        )
        assert host.backend == "host" and fused.backend == "fused"
        for tick in range(6):
            pts, masks, live = _fleet_inputs(streams, tick)
            eh = host.submit_points(pts, masks, live)
            ef = fused.submit_points(pts, masks, live)
            for s in range(streams):
                if eh[s] is None:
                    assert ef[s] is None
                    continue
                np.testing.assert_array_equal(eh[s].pose_q, ef[s].pose_q)
                assert eh[s].score == ef[s].score
                assert eh[s].matched_points == ef[s].matched_points
                assert eh[s].revision == ef[s].revision
        sh, sf = host.snapshot(), fused.snapshot()
        assert set(sh) == set(sf)
        for k in sh:
            np.testing.assert_array_equal(sh[k], sf[k])
        # structural: one dispatch per fleet tick, whatever the size
        assert fused.dispatch_count == 6

    def test_fused_matmul_voxel_backend_bit_exact(self):
        """The MXU-riding endpoint histogram (one-hot einsum) must land
        the exact same map as the host reference's scatter."""
        host = FleetMapper(_params(), 2, beams=BEAMS)
        fused = FleetMapper(
            _params(map_backend="fused", voxel_backend="matmul"),
            2, beams=BEAMS,
        )
        assert fused.cfg.voxel_backend == "matmul"
        for tick in range(4):
            pts, masks, live = _fleet_inputs(2, tick)
            host.submit_points(pts, masks, live)
            fused.submit_points(pts, masks, live)
        sh, sf = host.snapshot(), fused.snapshot()
        for k in sh:
            np.testing.assert_array_equal(sh[k], sf[k])

    def test_single_stream_jit_matches_host(self):
        """The non-vmapped single-stream program (ops/scan_match.
        map_match_step) is the same impl the fleet lowering vmaps —
        pin it against the host reference directly."""
        import jax

        from rplidar_ros2_driver_tpu.ops.scan_match import (
            MapState,
            map_match_step,
        )
        from rplidar_ros2_driver_tpu.ops.scan_match_ref import (
            create_map_state_np,
            map_match_step_np,
        )

        cfg = map_config_from_params(_params(), BEAMS)
        st_j = MapState.create(cfg)
        st_n = create_map_state_np(cfg)
        for tick in range(4):
            pts, m = _room_points((0.05 * tick, -0.02 * tick, 0.004 * tick))
            st_j, wire_j = map_match_step(
                st_j, pts, m, np.int32(1), cfg=cfg
            )
            st_n, wire_n = map_match_step_np(st_n, pts, m, 1, cfg)
            np.testing.assert_array_equal(np.asarray(wire_j), wire_n)
        got = jax.device_get(st_j)
        np.testing.assert_array_equal(
            np.asarray(got.log_odds), st_n["log_odds"]
        )
        np.testing.assert_array_equal(np.asarray(got.pose), st_n["pose"])


# ---------------------------------------------------------------------------
# robustness: degenerate inputs
# ---------------------------------------------------------------------------


class TestDegenerate:
    @pytest.mark.parametrize("backend", ["host", "fused"])
    def test_all_invalid_scan_keeps_map(self, backend):
        mapper = FleetMapper(_params(map_backend=backend), 1, beams=BEAMS)
        pts, m = _room_points((0, 0, 0))
        _submit_one(mapper, pts, m)
        before = mapper.snapshot()
        est = _submit_one(mapper, pts, np.zeros(BEAMS, bool))
        after = mapper.snapshot()
        assert est.score == 0 and est.matched_points == 0
        np.testing.assert_array_equal(
            before["log_odds"], after["log_odds"]
        )
        np.testing.assert_array_equal(before["pose"], after["pose"])
        # the revolution still counts (an observation happened)
        assert int(after["revision"][0]) == int(before["revision"][0]) + 1

    @pytest.mark.parametrize("backend", ["host", "fused"])
    def test_single_beam_scan_is_bounded(self, backend):
        mapper = FleetMapper(_params(map_backend=backend), 1, beams=BEAMS)
        pts = np.zeros((BEAMS, 2), np.float32)
        pts[0] = (1.0, 0.5)
        mask = np.zeros(BEAMS, bool)
        mask[0] = True
        est = _submit_one(mapper, pts, mask)
        assert est.matched_points == 1
        snap = mapper.snapshot()
        lo = snap["log_odds"][0]
        cfg = mapper.cfg
        # one endpoint + its ray samples: a handful of touched cells,
        # all within the clamp
        assert 0 < np.count_nonzero(lo) <= cfg.free_samples + 1
        assert np.abs(lo).max() <= cfg.clamp_q

    def test_idle_stream_passes_through(self):
        mapper = FleetMapper(_params(), 2, beams=BEAMS)
        pts, m = _room_points((0, 0, 0))
        stacked = np.stack([pts, pts])
        masks = np.stack([m, m])
        mapper.submit_points(stacked, masks, np.asarray([1, 1], np.int32))
        before = mapper.snapshot()
        ests = mapper.submit_points(
            stacked, masks, np.asarray([1, 0], np.int32)
        )
        assert ests[1] is None
        after = mapper.snapshot()
        np.testing.assert_array_equal(
            before["log_odds"][1], after["log_odds"][1]
        )
        assert int(after["revision"][1]) == int(before["revision"][1])
        assert int(after["revision"][0]) == int(before["revision"][0]) + 1

    @pytest.mark.parametrize(
        "value", [1.0e6, 3.0e18, np.inf, -np.inf, np.nan]
    )
    def test_far_or_nonfinite_points_dropped_not_wrapped(self, value):
        """Points beyond the fixed-point window — or outright
        non-finite — must be invalidated, never cast to int32 (the
        cast of an out-of-range f32 is implementation-defined and
        NumPy/XLA disagree, which would poison the parity contract)."""
        for backend in ("host", "fused"):
            mapper = FleetMapper(
                _params(map_backend=backend), 1, beams=BEAMS
            )
            pts = np.full((BEAMS, 2), value, np.float32)
            mask = np.ones(BEAMS, bool)
            est = _submit_one(mapper, pts, mask)
            assert est.matched_points == 0
            assert np.count_nonzero(mapper.snapshot()["log_odds"]) == 0


# ---------------------------------------------------------------------------
# checkpoint surface
# ---------------------------------------------------------------------------


class TestCheckpoint:
    @pytest.mark.parametrize("backend", ["host", "fused"])
    def test_snapshot_restore_mid_run_resumes_bit_exact(self, backend):
        p = _params(map_backend=backend)
        mapper = FleetMapper(p, 2, beams=BEAMS)
        for tick in range(3):
            pts, masks, live = _fleet_inputs(2, tick)
            mapper.submit_points(pts, masks, live)
        snap = mapper.snapshot()
        ref_tail = []
        for tick in range(3, 5):
            pts, masks, live = _fleet_inputs(2, tick)
            ref_tail.append(mapper.submit_points(pts, masks, live))
        ref_final = mapper.snapshot()

        resumed = FleetMapper(p, 2, beams=BEAMS)
        assert resumed.restore(snap) is True
        for tick, ref in zip(range(3, 5), ref_tail):
            pts, masks, live = _fleet_inputs(2, tick)
            got = resumed.submit_points(pts, masks, live)
            for s in range(2):
                if ref[s] is None:
                    assert got[s] is None
                else:
                    np.testing.assert_array_equal(
                        ref[s].pose_q, got[s].pose_q
                    )
        got_final = resumed.snapshot()
        for k in ref_final:
            np.testing.assert_array_equal(ref_final[k], got_final[k])

    def test_cross_backend_restore(self):
        """A host snapshot restores into a fused mapper (and back) —
        the snapshot format is backend-independent."""
        host = FleetMapper(_params(), 1, beams=BEAMS)
        pts, m = _room_points((0.1, 0, 0))
        _submit_one(host, pts, m)
        snap = host.snapshot()
        fused = FleetMapper(_params(map_backend="fused"), 1, beams=BEAMS)
        assert fused.restore(snap) is True
        back = fused.snapshot()
        for k in snap:
            np.testing.assert_array_equal(snap[k], back[k])

    def test_restore_rejects_mismatch_untouched(self):
        mapper = FleetMapper(_params(), 1, beams=BEAMS)
        pts, m = _room_points((0, 0, 0))
        _submit_one(mapper, pts, m)
        before = mapper.snapshot()
        other = FleetMapper(_params(map_grid=32), 1, beams=BEAMS)
        assert other.restore(before) is False  # wrong geometry
        bad_version = dict(before)
        bad_version["version"] = np.asarray(99, np.int32)
        assert mapper.restore(bad_version) is False  # future schema
        after = mapper.snapshot()
        for k in before:
            np.testing.assert_array_equal(before[k], after[k])

    def test_npz_roundtrip(self, tmp_path):
        from rplidar_ros2_driver_tpu.utils.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        mapper = FleetMapper(_params(), 1, beams=BEAMS)
        pts, m = _room_points((0.2, -0.1, 0.01))
        _submit_one(mapper, pts, m)
        snap = mapper.snapshot()
        path = str(tmp_path / "map.npz")
        save_checkpoint(path, snap)
        loaded, _meta = load_checkpoint(path)
        resumed = FleetMapper(_params(), 1, beams=BEAMS)
        assert resumed.restore(loaded) is True
        got = resumed.snapshot()
        for k in snap:
            np.testing.assert_array_equal(snap[k], got[k])


class TestNodeWiring:
    def _fake_output(self, beams=2048):
        from rplidar_ros2_driver_tpu.ops.filters import FilterOutput

        pts, m = _room_points((0, 0, 0), n=beams)
        return FilterOutput(
            ranges=np.linalg.norm(pts, axis=1).astype(np.float32),
            intensities=np.full(beams, 47.0, np.float32),
            points_xy=pts,
            point_mask=m,
            voxel=np.zeros((32, 32), np.int32),
        )

    def _node_params(self):
        return _params(voxel_grid_size=32, filter_window=2)

    def test_node_publishes_pose_and_diagnostics(self):
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode

        node = RPlidarNode(self._node_params())
        assert node.configure()
        assert node.mapper is not None
        node._publish_chain_output(self._fake_output(), 1.0, 0.1, 8.0)
        assert node.publisher.poses
        pose = node.publisher.poses[-1]
        assert pose.frame_id == "map" and pose.map_revision == 1
        node._update_diagnostics()
        values = node.publisher.diagnostics[-1].values
        assert values.get("Map Backend") == node.mapper.backend
        assert "Map Pose" in values

    def test_node_checkpoint_roundtrips_map(self, tmp_path):
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode

        node = RPlidarNode(self._node_params())
        assert node.configure()
        node._publish_chain_output(self._fake_output(), 1.0, 0.1, 8.0)
        want = node.mapper.snapshot()
        path = str(tmp_path / "node_ckpt.npz")
        assert node.save_checkpoint(path) is True

        fresh = RPlidarNode(self._node_params())
        assert fresh.load_checkpoint(path) is True
        assert fresh.configure()
        got = fresh.mapper.snapshot()
        for k in want:
            np.testing.assert_array_equal(want[k], got[k])

    def test_node_checkpoint_without_mapper_still_loads_chain(self, tmp_path):
        """A checkpoint saved without map keys (mapper off) loads into a
        map-enabled node: chain restored, mapper starts cold."""
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode

        plain = _params(voxel_grid_size=32, filter_window=2, map_enable=False)
        node = RPlidarNode(plain)
        assert node.configure()
        path = str(tmp_path / "plain.npz")
        assert node.save_checkpoint(path) is True

        mapped = RPlidarNode(self._node_params())
        assert mapped.load_checkpoint(path) is True
        assert mapped.configure()
        assert int(mapped.mapper.snapshot()["revision"][0]) == 0


# ---------------------------------------------------------------------------
# fleet service seam + viz + replay
# ---------------------------------------------------------------------------


def _scan(k: int, points: int = 300) -> dict:
    rng = np.random.default_rng(k)
    return {
        "angle_q14": ((np.arange(points) * 65536) // points).astype(np.int32),
        "dist_q2": (rng.uniform(0.3, 8.0, points) * 4000).astype(np.int32),
        "quality": np.full(points, 180, np.int32),
        "flag": None,
    }


def test_service_attach_mapper():
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
    from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh

    svc = ShardedFilterService(
        _params(filter_window=2, voxel_grid_size=32),
        streams=2, mesh=make_mesh(2), beams=128,
    )
    mapper = svc.attach_mapper()
    assert mapper.streams == 2
    svc.submit([_scan(1), _scan(2)])
    assert all(p is not None for p in svc.last_poses)
    assert mapper.ticks == 1
    svc.submit([_scan(3), None])  # idle stream rides through
    assert svc.last_poses[1] is None


def test_service_pipelined_flush_feeds_mapper():
    """The run's FINAL in-flight pipelined tick must reach the mapper at
    flush time, or the map ends one revolution short of a non-pipelined
    run over the same input (code-review finding)."""
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
    from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh

    svc = ShardedFilterService(
        _params(filter_window=2, voxel_grid_size=32),
        streams=2, mesh=make_mesh(2), beams=128,
    )
    mapper = svc.attach_mapper()
    svc.submit_pipelined([_scan(1), _scan(2)])  # dispatched, nothing back yet
    svc.submit_pipelined([_scan(3), _scan(4)])  # returns + maps tick 1
    assert mapper.ticks == 1
    svc.flush_pipelined()                       # drains + maps tick 2
    assert mapper.ticks == 2
    assert int(mapper.snapshot()["revision"][0]) == 2


def test_service_fused_backlog_feeds_mapper_like_host():
    """A backlog drained through the FUSED fleet ingest must leave the
    attached mapper in the same state as the host golden path over the
    same ticks (code-review finding: the fused branch used to bypass
    the mapper entirely, making mapper state backend-dependent)."""
    import bench
    from rplidar_ros2_driver_tpu.protocol.constants import Ans
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService

    ans = int(Ans.MEASUREMENT_DENSE_CAPSULED)
    frames = bench._denseboost_wire_frames(4, 400)  # 4 revs, 10 frames each
    run = 10

    def make_ticks():
        t = [100.0, 200.0]
        ticks = []
        for i in range(0, len(frames), run):
            tick = []
            for s in range(2):
                batch = []
                for f in frames[i : i + run]:
                    t[s] += 1e-3
                    batch.append((f, t[s]))
                tick.append((ans, batch))
            ticks.append(tick)
        return ticks

    def run_backend(backend):
        svc = ShardedFilterService(
            _params(
                filter_window=2, voxel_grid_size=32,
                fleet_ingest_backend=backend,
            ),
            streams=2, beams=128, capacity=512,
            fleet_ingest_buckets=(run,),
        )
        m = svc.attach_mapper()
        svc.submit_bytes_backlog(make_ticks())
        return m

    mh, mf = run_backend("host"), run_backend("fused")
    sh, sf = mh.snapshot(), mf.snapshot()
    assert (np.asarray(sh["revision"]) > 0).all()  # revolutions absorbed
    for k in sh:
        np.testing.assert_array_equal(sh[k], sf[k])
    assert all(e is not None for e in mf.last_estimates)


def test_viz_map_render_and_trajectory():
    from rplidar_ros2_driver_tpu.tools.viz import draw_trajectory, map_to_image

    mapper = FleetMapper(_params(), 1, beams=BEAMS)
    pts, m = _room_points((0, 0, 0))
    _submit_one(mapper, pts, m)
    snap = mapper.snapshot()
    img = map_to_image(snap["log_odds"][0], mapper.cfg.clamp_q)
    assert img.shape == (64, 64) and img.dtype == np.uint8
    assert (img > 128).any()   # occupied walls
    assert (img < 128).any()   # freed interior
    over = draw_trajectory(
        img, [(0.0, 0.0), (0.5, 0.5)], mapper.cfg.cell_m, value=255
    )
    assert (over == 255).sum() >= 1
    assert img.shape == over.shape


def test_replay_with_map():
    from rplidar_ros2_driver_tpu.replay import replay_with_map

    revs = [_scan(k, points=600) for k in range(5)]
    traj, scores, mapper = replay_with_map(
        revs, _params(filter_window=2, voxel_grid_size=32), beams=256
    )
    assert traj.shape == (5, 3) and np.isfinite(traj).all()
    assert scores.shape == (5,)
    assert int(mapper.snapshot()["revision"][0]) == 5
    assert np.count_nonzero(mapper.snapshot()["log_odds"]) > 0
