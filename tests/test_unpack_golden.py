"""Golden equivalence: vectorized JAX unpackers vs the scalar reference
decoders, over randomized wire streams including scan restarts and
corruption.  This is the bit-exactness contract for the fixed-point math
(SURVEY.md §7 'fixed-point parity')."""

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.ops import unpack, unpack_ref, wire


def _rng():
    return np.random.default_rng(1234)


def _frames_to_array(frames):
    return np.frombuffer(b"".join(frames), np.uint8).reshape(len(frames), -1)


def _angles(rng, m, step_q6=640):
    """Monotonic wrapped start angles with jitter, like a spinning head."""
    inc = rng.integers(step_q6 // 2, step_q6 * 2, m)
    return (np.cumsum(inc) + rng.integers(0, 360 << 6)) % (360 << 6)


def _collect_ref(decoder, frames):
    """Run the stateful scalar decoder over the stream, keeping per-frame
    node lists aligned to JAX's pair indexing (pair i -> nodes of frame i)."""
    per_pair = []
    for fr in frames:
        nodes, _ = decoder.decode(fr)
        per_pair.append(nodes)
    return per_pair


def _compare(dec, per_pair_ref, npts):
    """per_pair_ref[i+1] holds nodes for pair i (emitted when cur arrived)."""
    angle = np.asarray(dec.angle_q14)
    dist = np.asarray(dec.dist_q2)
    qual = np.asarray(dec.quality)
    flag = np.asarray(dec.flag)
    valid = np.asarray(dec.node_valid)
    m = angle.shape[0]
    for i in range(m):
        ref_nodes = per_pair_ref[i + 1]
        if not ref_nodes:
            assert not valid[i].any(), f"pair {i}: JAX valid but reference emitted nothing"
            continue
        assert valid[i].all(), f"pair {i}: reference emitted nodes but JAX masked"
        assert len(ref_nodes) == npts
        for k, n in enumerate(ref_nodes):
            assert angle[i, k] == n.angle_q14, (i, k, angle[i, k], n.angle_q14)
            assert dist[i, k] == n.dist_q2, (i, k, dist[i, k], n.dist_q2)
            assert qual[i, k] == n.quality, (i, k)
            assert flag[i, k] == n.flag, (i, k, flag[i, k], n.flag)


class TestNormalNodes:
    def test_golden(self):
        rng = _rng()
        frames = []
        expected = []
        for i in range(100):
            angle_q6 = int(rng.integers(0, 360 << 6))
            dist_q2 = int(rng.integers(0, 1 << 16))
            quality6 = int(rng.integers(0, 64))
            fr = wire.encode_normal_node(angle_q6, dist_q2, quality6, syncbit=(i % 37 == 0))
            frames.append(fr)
            expected.append(unpack_ref.decode_normal_node(fr))
        dec = unpack.unpack_normal_nodes(_frames_to_array(frames))
        for i, exp in enumerate(expected):
            assert exp is not None
            assert np.asarray(dec.node_valid)[i, 0]
            assert np.asarray(dec.angle_q14)[i, 0] == exp.angle_q14
            assert np.asarray(dec.dist_q2)[i, 0] == exp.dist_q2
            assert np.asarray(dec.quality)[i, 0] == exp.quality
            assert np.asarray(dec.flag)[i, 0] == exp.flag

    def test_bad_sync_bits_rejected(self):
        fr = bytearray(wire.encode_normal_node(100, 100, 10, False))
        fr[0] |= 0x3  # sync and inverse both set -> invalid
        dec = unpack.unpack_normal_nodes(np.frombuffer(bytes(fr), np.uint8)[None, :])
        assert unpack_ref.decode_normal_node(bytes(fr)) is None
        assert not np.asarray(dec.node_valid)[0, 0]


class TestCapsules:
    def _make_stream(self, rng, m=24, corrupt=(), syncs=()):
        starts = _angles(rng, m)
        frames = []
        for i in range(m):
            dist = rng.integers(0, 1 << 14, (16, 2)) << 2
            dist[rng.random((16, 2)) < 0.1] = 0  # invalid points
            off = rng.integers(0, 64, (16, 2))
            fr = bytearray(
                wire.encode_capsule(int(starts[i]), i in syncs, dist, off)
            )
            if i in corrupt:
                fr[10] ^= 0xFF
            frames.append(bytes(fr))
        return frames

    @pytest.mark.parametrize("corrupt,syncs", [((), (0,)), ((), (0, 7)), ((5,), (0,)), ((3, 4), (0, 9))])
    def test_golden(self, corrupt, syncs):
        rng = _rng()
        frames = self._make_stream(rng, corrupt=corrupt, syncs=syncs)
        ref = _collect_ref(unpack_ref.CapsuleDecoder(), frames)
        dec = unpack.unpack_capsules(_frames_to_array(frames))
        _compare(dec, ref, 32)


class TestUltraCapsules:
    def _make_stream(self, rng, m=16, syncs=(0,), corrupt=()):
        starts = _angles(rng, m, step_q6=1920)
        frames = []
        for i in range(m):
            major = rng.integers(0, 4096, 32)
            p1 = rng.integers(-512, 512, 32)
            p2 = rng.integers(-512, 512, 32)
            fr = bytearray(
                wire.encode_ultra_capsule(int(starts[i]), i in syncs, major, p1, p2)
            )
            if i in corrupt:
                fr[40] ^= 0x55
            frames.append(bytes(fr))
        return frames

    @pytest.mark.parametrize("corrupt,syncs", [((), (0,)), ((6,), (0, 11))])
    def test_golden(self, corrupt, syncs):
        rng = _rng()
        frames = self._make_stream(rng, corrupt=corrupt, syncs=syncs)
        ref = _collect_ref(unpack_ref.UltraCapsuleDecoder(), frames)
        dec = unpack.unpack_ultra_capsules(_frames_to_array(frames))
        _compare(dec, ref, 96)

    def test_varbitscale_roundtrip(self):
        for lvl_base in (0, 300, 600, 1400, 2000, 3500, 4095):
            val, lvl = unpack_ref.varbitscale_decode(lvl_base)
            assert wire.varbitscale_encode(val) == lvl_base


class TestDenseCapsules:
    def _make_stream(self, rng, m=24, syncs=(0,), corrupt=(), jump_at=None):
        starts = _angles(rng, m, step_q6=900)
        if jump_at is not None:
            starts[jump_at] = (starts[jump_at - 1] + (300 << 6)) % (360 << 6)
        frames = []
        for i in range(m):
            dist = rng.integers(0, 1 << 15, 40)
            dist[rng.random(40) < 0.05] = 0
            fr = bytearray(wire.encode_dense_capsule(int(starts[i]), i in syncs, dist))
            if i in corrupt:
                fr[30] ^= 0x0F
            frames.append(bytes(fr))
        return frames

    @pytest.mark.parametrize(
        "corrupt,syncs,jump_at",
        [((), (0,), None), ((4,), (0, 13), None), ((), (0,), 8)],
    )
    def test_golden(self, corrupt, syncs, jump_at):
        rng = _rng()
        frames = self._make_stream(rng, corrupt=corrupt, syncs=syncs, jump_at=jump_at)
        ref_dec = unpack_ref.DenseCapsuleDecoder(sample_duration_us=476)
        ref = _collect_ref(ref_dec, frames)
        dec = unpack.unpack_dense_capsules(_frames_to_array(frames), 0, 476)
        _compare(dec, ref, 40)


class TestUltraDenseCapsules:
    def _make_stream(self, rng, m=16, syncs=(0,), corrupt=()):
        starts = _angles(rng, m, step_q6=1200)
        frames = []
        for i in range(m):
            # mix of scales; include near-equal consecutive distances to
            # exercise the +/-2 mm smoothing recurrence
            base = int(rng.integers(100, 2000))
            dmm = base + rng.integers(-2, 3, 64).cumsum() % 30000
            qual = rng.integers(0, 256, 64)
            words = np.array(
                [wire.ultra_dense_encode_sample(int(d), int(q)) for d, q in zip(dmm, qual)]
            )
            fr = bytearray(
                wire.encode_ultra_dense_capsule(int(starts[i]), i in syncs, words)
            )
            if i in corrupt:
                fr[60] ^= 0xF0
            frames.append(bytes(fr))
        return frames

    @pytest.mark.parametrize("corrupt,syncs", [((), (0,)), ((5,), (0, 9))])
    def test_golden(self, corrupt, syncs):
        rng = _rng()
        frames = self._make_stream(rng, corrupt=corrupt, syncs=syncs)
        ref_dec = unpack_ref.UltraDenseCapsuleDecoder(sample_duration_us=476)
        ref = _collect_ref(ref_dec, frames)
        dec = unpack.unpack_ultra_dense_capsules(_frames_to_array(frames), 0, 0, 476)
        _compare(dec, ref, 64)


class TestHqCapsules:
    def test_golden(self):
        rng = _rng()
        frames = []
        for i in range(8):
            fr = wire.encode_hq_capsule(
                rng.integers(0, 1 << 16, 96),
                rng.integers(0, 1 << 18, 96),
                rng.integers(0, 256, 96),
                np.where(np.arange(96) == 0, i % 2, 2),
                timestamp=1000 * i,
            )
            frames.append(fr)
        arr = _frames_to_array(frames)
        crc_ok = []
        ref_nodes = []
        for fr in frames:
            nodes, _ = unpack_ref.decode_hq_capsule(fr)
            crc_ok.append(bool(nodes))
            ref_nodes.append(nodes)
        dec = unpack.unpack_hq_capsules(arr, np.array(crc_ok))
        for i in range(8):
            assert np.asarray(dec.node_valid)[i].all()
            for k, n in enumerate(ref_nodes[i]):
                assert np.asarray(dec.angle_q14)[i, k] == n.angle_q14
                assert np.asarray(dec.dist_q2)[i, k] == n.dist_q2
                assert np.asarray(dec.quality)[i, k] == n.quality
                assert np.asarray(dec.flag)[i, k] == n.flag

    def test_crc_reject(self):
        fr = bytearray(
            wire.encode_hq_capsule(
                np.zeros(96), np.zeros(96), np.zeros(96), np.zeros(96)
            )
        )
        fr[100] ^= 1
        nodes, _ = unpack_ref.decode_hq_capsule(bytes(fr))
        assert nodes == []


class TestSyncEdgeDivergenceBound:
    """Pin the documented dense/ultra-dense carry-chain divergence window
    (ops/unpack.py dense sync note): the vectorized decoders zero a
    discarded pair's sync inputs to keep the batch carry aligned, while
    the scalar model (like the reference's per-sample filter,
    handler_capsules.cpp:738,766) simply never sees dropped samples.  A
    sync region straddling a dropped capsule can therefore re-fire the
    edge once on the far side — at most ONE extra flag per dropped
    frame, and zero drift anywhere else.  These streams are engineered
    so the revolution wrap lands exactly across the dropped frames (the
    only geometry where the decoders can disagree)."""

    M, J = 12, 6  # stream length, corrupted frame index

    def _starts(self):
        """900-q6 steps, except frames J-1..J+1 stall just past the 0
        wrap: the last samples of pair J-2 sit inside the sync window
        below 0, and pair J+1's first sample sits inside it above 0."""
        j = self.J
        starts = []
        for i in range(self.M):
            if i < j - 1:
                starts.append(22145 - 900 * (j - 2) + 900 * i)
            elif i <= j + 1:
                starts.append(5 + 2 * (i - (j - 1)))
            else:
                starts.append(909 + 900 * (i - j - 1))
        return [s % (360 << 6) for s in starts]

    def _flag_drift(self, dec, per_pair_ref, npts):
        """(flag mismatches, any-other-field mismatches) between the JAX
        decode and the scalar model, over pairs both emitted."""
        angle = np.asarray(dec.angle_q14)
        dist = np.asarray(dec.dist_q2)
        qual = np.asarray(dec.quality)
        flag = np.asarray(dec.flag)
        valid = np.asarray(dec.node_valid)
        drift = others = 0
        for i in range(angle.shape[0]):
            ref_nodes = per_pair_ref[i + 1]
            if not ref_nodes:
                others += int(valid[i].any())
                continue
            if not valid[i].all() or len(ref_nodes) != npts:
                others += 1
                continue
            for k, n in enumerate(ref_nodes):
                if flag[i, k] != n.flag:
                    drift += 1
                if (
                    angle[i, k] != n.angle_q14
                    or dist[i, k] != n.dist_q2
                    or qual[i, k] != n.quality
                ):
                    others += 1
        return drift, others

    def _dense_frames(self, corrupt, starts=None):
        rng = _rng()
        frames = []
        for i, s in enumerate(starts if starts is not None else self._starts()):
            fr = bytearray(
                wire.encode_dense_capsule(int(s), i == 0, rng.integers(1, 1 << 15, 40))
            )
            if i in corrupt:
                fr[30] ^= 0x0F
            frames.append(bytes(fr))
        return frames

    def _ud_frames(self, corrupt):
        rng = _rng()
        frames = []
        for i, s in enumerate(self._starts()):
            dmm = rng.integers(100, 2000, 64)
            qual = rng.integers(0, 256, 64)
            words = np.array([
                wire.ultra_dense_encode_sample(int(d), int(q))
                for d, q in zip(dmm, qual)
            ])
            fr = bytearray(wire.encode_ultra_dense_capsule(s, i == 0, words))
            if i in corrupt:
                fr[60] ^= 0xF0
            frames.append(bytes(fr))
        return frames

    def test_dense_drift_is_exactly_one_flag(self):
        # no corruption: the same geometry decodes bit-identically
        clean = self._dense_frames(())
        ref = _collect_ref(unpack_ref.DenseCapsuleDecoder(sample_duration_us=476), clean)
        dec = unpack.unpack_dense_capsules(_frames_to_array(clean), 0, 476)
        assert self._flag_drift(dec, ref, 40) == (0, 0)
        # dropped capsule under the wrap: one re-fired flag, nothing else
        bad = self._dense_frames((self.J,))
        ref = _collect_ref(unpack_ref.DenseCapsuleDecoder(sample_duration_us=476), bad)
        dec = unpack.unpack_dense_capsules(_frames_to_array(bad), 0, 476)
        assert self._flag_drift(dec, ref, 40) == (1, 0)

    def test_ultra_dense_drift_is_exactly_one_flag(self):
        clean = self._ud_frames(())
        ref = _collect_ref(
            unpack_ref.UltraDenseCapsuleDecoder(sample_duration_us=476), clean
        )
        dec = unpack.unpack_ultra_dense_capsules(_frames_to_array(clean), 0, 0, 476)
        assert self._flag_drift(dec, ref, 64) == (0, 0)
        bad = self._ud_frames((self.J,))
        ref = _collect_ref(
            unpack_ref.UltraDenseCapsuleDecoder(sample_duration_us=476), bad
        )
        dec = unpack.unpack_ultra_dense_capsules(_frames_to_array(bad), 0, 0, 476)
        assert self._flag_drift(dec, ref, 64) == (1, 0)

    def test_drift_bounded_by_dropped_frames_random_streams(self):
        """Randomized geometries: drift never exceeds one flag per
        corrupted frame (and is usually zero — the wrap rarely straddles
        the drop)."""
        for seed in range(4):
            rng = np.random.default_rng(seed)
            starts = _angles(rng, self.M, step_q6=900)
            corrupt = (3, 8)
            frames = self._dense_frames(corrupt, starts=starts)
            ref = _collect_ref(
                unpack_ref.DenseCapsuleDecoder(sample_duration_us=476), frames
            )
            dec = unpack.unpack_dense_capsules(_frames_to_array(frames), 0, 476)
            drift, others = self._flag_drift(dec, ref, 40)
            assert others == 0
            assert drift <= len(corrupt), (seed, drift)
