"""Record/replay tests: capture format, driver tee, batched offline decode.

The strongest check: record frames from the protocol simulator through
the REAL driver while the online scalar decoders assemble scans, then
batch-decode the capture with the vectorized kernels — both paths must
produce the same valid nodes.
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.protocol.constants import Ans
from rplidar_ros2_driver_tpu.replay import (
    FrameRecorder,
    decode_recording,
    read_frames,
)


class TestFormat:
    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "cap.rplr")
        with FrameRecorder(p) as rec:
            rec.write(0x81, b"\x01\x02\x03\x04\x05", 1.5)
            rec.write(0x82, b"\xff" * 84, 2.0)
        got = list(read_frames(p))
        assert got == [(0x81, 1.5, b"\x01\x02\x03\x04\x05"), (0x82, 2.0, b"\xff" * 84)]

    def test_torn_tail_stops_cleanly(self, tmp_path):
        p = str(tmp_path / "cap.rplr")
        with FrameRecorder(p) as rec:
            rec.write(0x81, b"\x01" * 5, 1.0)
            rec.write(0x81, b"\x02" * 5, 2.0)
        raw = open(p, "rb").read()
        with open(p, "wb") as f:
            f.write(raw[:-3])  # cut into the final payload
        got = list(read_frames(p))
        assert len(got) == 1

    def test_bad_magic_raises(self, tmp_path):
        p = tmp_path / "junk.bin"
        p.write_bytes(b"NOPE" + b"\x00" * 16)
        with pytest.raises(ValueError):
            list(read_frames(str(p)))

    def test_empty_file_ok(self, tmp_path):
        p = tmp_path / "empty.rplr"
        p.write_bytes(b"")
        assert list(read_frames(str(p))) == []


class TestTailTruncation:
    """The format's claimed tail-truncation safety, pinned case by case:
    a capture cut ANYWHERE (crash mid-write, full disk) must yield clean
    partial iteration up to the last whole record — never raise, never
    yield a torn record."""

    def _capture_bytes(self, tmp_path) -> bytes:
        p = str(tmp_path / "full.rplr")
        with FrameRecorder(p) as rec:
            rec.write(0x81, b"\x01" * 5, 1.0)
            rec.write(0x85, b"\x02" * 84, 2.0)
        return open(p, "rb").read()

    def _cut(self, tmp_path, raw: bytes, n: int) -> list:
        p = str(tmp_path / f"cut{n}.rplr")
        with open(p, "wb") as f:
            f.write(raw[:n])
        return list(read_frames(p))

    def test_zero_length_capture(self, tmp_path):
        p = tmp_path / "zero.rplr"
        p.write_bytes(b"")
        assert list(read_frames(str(p))) == []

    def test_truncated_file_header(self, tmp_path):
        """A cut inside the 8-byte file header (even mid-magic) is a
        clean empty iteration, not a struct error or a magic raise."""
        raw = self._capture_bytes(tmp_path)
        from rplidar_ros2_driver_tpu import replay as R

        for n in range(R._HEADER.size):
            assert self._cut(tmp_path, raw, n) == [], n

    def test_truncated_record_header(self, tmp_path):
        """A cut inside the SECOND record's 12-byte header keeps the
        first record and stops cleanly."""
        raw = self._capture_bytes(tmp_path)
        from rplidar_ros2_driver_tpu import replay as R

        first_end = R._HEADER.size + R._REC.size + 5
        for n in range(first_end, first_end + R._REC.size):
            got = self._cut(tmp_path, raw, n)
            assert got == [(0x81, 1.0, b"\x01" * 5)], n

    def test_truncated_payload(self, tmp_path):
        """A cut inside the second record's payload (any prefix of it,
        including zero bytes present) likewise keeps only the first."""
        raw = self._capture_bytes(tmp_path)
        from rplidar_ros2_driver_tpu import replay as R

        second_payload = R._HEADER.size + 2 * R._REC.size + 5
        for n in range(second_payload, len(raw)):  # incl. one-byte-short
            got = self._cut(tmp_path, raw, n)
            assert got == [(0x81, 1.0, b"\x01" * 5)], n
        # the uncut file yields both, proving the cuts above did the work
        assert len(self._cut(tmp_path, raw, len(raw))) == 2


def _capture_from_sim(tmp_path, seconds=1.2, name="sim.rplr"):
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice

    path = str(tmp_path / name)
    sim = SimulatedDevice().start()
    online_scans = []
    try:
        drv = RealLidarDriver(
            channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
            motor_warmup_s=0.0,
        )
        assert drv.connect("sim", 0, False)  # no ascend: keep raw node order
        drv.start_recording(path)
        assert drv.start_motor("", 600)
        deadline = time.monotonic() + 10
        while len(online_scans) < 3 and time.monotonic() < deadline:
            got = drv.grab_scan_host(2.0)
            if got is not None:
                online_scans.append(got[0])
        frames = drv.stop_recording()
        assert frames and frames > 0
        drv.stop_motor()
        drv.disconnect()
    finally:
        sim.stop()
    return path, online_scans


class TestEndToEnd:
    def test_batch_decode_matches_online(self, tmp_path):
        path, online = _capture_from_sim(tmp_path)
        assert online
        dec = decode_recording(path)
        assert dec.num_nodes > 0
        revs = dec.revolutions()
        assert revs
        # the online scans (complete revolutions) must appear, node-exact,
        # in the batched offline decode
        online_concat = np.concatenate([s["angle_q14"] for s in online])
        offline_concat = np.concatenate([r["angle_q14"] for r in revs])
        # find the online stream inside the offline stream (offline saw
        # every frame; online may have dropped leading/lagging partials)
        s_on = online_concat.tobytes()
        s_off = offline_concat.tobytes()
        idx = s_off.find(s_on)
        assert idx >= 0 and idx % 4 == 0, "online nodes not found in offline decode"
        start = idx // 4
        n = len(online_concat)
        for key in ("dist_q2", "quality"):
            on = np.concatenate([s[key] for s in online])
            off = np.concatenate([r[key] for r in revs])[start : start + n]
            np.testing.assert_array_equal(on, off)

    def test_runs_report_format(self, tmp_path):
        path, _ = _capture_from_sim(tmp_path, seconds=0.5)
        dec = decode_recording(path)
        assert dec.runs
        ans_type, n_frames, n_nodes = dec.runs[0]
        assert ans_type in (int(a) for a in Ans)
        assert n_frames > 0 and n_nodes >= 0

    def test_cli_replay(self, tmp_path):
        path, _ = _capture_from_sim(tmp_path, seconds=0.5)
        out = subprocess.run(
            [sys.executable, "-m", "rplidar_ros2_driver_tpu", "replay", path, "--cpu"],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "complete revolutions" in out.stdout
        assert "run:" in out.stdout

    def test_cli_replay_through_chain(self, tmp_path):
        path, _ = _capture_from_sim(tmp_path, seconds=0.5)
        out = subprocess.run(
            [sys.executable, "-m", "rplidar_ros2_driver_tpu", "replay", path,
             "--cpu", "--chain"],
            capture_output=True,
            text=True,
            timeout=180,
        )
        assert out.returncode == 0, out.stderr
        assert "fused multi-scan step" in out.stdout
        assert "voxel occupancy" in out.stdout

    def test_cli_replay_fleet(self, tmp_path):
        """Two recordings replay as one fleet over the mesh."""
        p1, _ = _capture_from_sim(tmp_path, seconds=0.5, name="a.rplr")
        p2, _ = _capture_from_sim(tmp_path, seconds=0.5, name="b.rplr")
        out = subprocess.run(
            [sys.executable, "-m", "rplidar_ros2_driver_tpu", "replay", p1, p2,
             "--cpu", "--chain"],
            capture_output=True,
            text=True,
            timeout=180,
        )
        assert out.returncode == 0, out.stderr
        assert "sharded fleet replay (2 streams)" in out.stdout
        assert "voxel occupancy" in out.stdout

    @pytest.mark.slow  # tier-1 covers replay_with_map + viz directly
    # (tests/test_mapping.py); this subprocess arm costs ~15 s of a
    # budget the suite already crowds
    def test_cli_replay_map(self, tmp_path):
        """`replay --map`: capture -> chain -> SLAM front-end, map as a
        PGM artifact with the trajectory overlay — inspectable with no
        ROS anywhere in the loop."""
        path, _ = _capture_from_sim(tmp_path, seconds=0.5)
        pgm = str(tmp_path / "map.pgm")
        out = subprocess.run(
            [sys.executable, "-m", "rplidar_ros2_driver_tpu", "replay", path,
             "--cpu", "--map", "--map-pgm", pgm],
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert out.returncode == 0, out.stderr
        assert "mapped" in out.stdout and "final pose" in out.stdout
        with open(pgm, "rb") as f:
            assert f.read(2) == b"P5"


def test_write_after_close_is_noop(tmp_path):
    rec = FrameRecorder(str(tmp_path / "c.rplr"))
    rec.write(0x81, b"\x01" * 5)
    rec.close()
    rec.write(0x81, b"\x02" * 5)  # racing decode thread: silently dropped
    assert rec.frames == 1


@pytest.mark.parametrize("mode_name,expect_ans", [
    ("DenseBoost", 0x85),     # dense capsules (40 pts/frame)
    ("Sensitivity", 0x82),    # express capsules (16 cabins x 2)
    ("UltraBoost", 0x84),     # ultra capsules (32 cabins x 3)
    ("UltraDense", 0x86),     # ultra-dense capsules (32 cabins x 2)
    ("HQ", 0x83),             # HQ capsules (96 nodes + CRC32)
])
def test_capture_capsule_formats(tmp_path, mode_name, expect_ans):
    """Capture + batch-decode the capsule wire formats end-to-end: the
    offline vectorized decode must reproduce the online scalar decode."""
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice

    path = str(tmp_path / f"{mode_name}.rplr")
    sim = SimulatedDevice().start()
    online = []
    try:
        drv = RealLidarDriver(
            channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
            motor_warmup_s=0.0,
        )
        assert drv.connect("sim", 0, False)
        drv.detect_and_init_strategy()
        drv.start_recording(path)
        assert drv.start_motor(mode_name, 600)
        assert drv.profile.active_mode == mode_name
        deadline = time.monotonic() + 15
        while len(online) < 2 and time.monotonic() < deadline:
            got = drv.grab_scan_host(2.0)
            if got is not None:
                online.append(got[0])
        assert drv.stop_recording() > 0
        drv.stop_motor()
        drv.disconnect()
    finally:
        sim.stop()
    assert online

    dec = decode_recording(path)
    assert any(a == expect_ans for a, _, _ in dec.runs), dec.runs
    revs = dec.revolutions()
    assert revs
    # online nodes must appear node-exact inside the offline batch decode
    on = np.concatenate([s["dist_q2"] for s in online])
    off = np.concatenate([r["dist_q2"] for r in revs])
    idx = off.tobytes().find(on.tobytes())
    assert idx >= 0 and idx % 4 == 0, f"{mode_name}: online nodes not in offline decode"


def test_ultra_mode_geometry_matches_standard(tmp_path):
    """The emulator's ultra mode must describe the SAME scene as Standard:
    the varbitscale/predict encoding is mm-domain and quantized, so decoded
    ranges agree within the coarsest scale step."""
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice

    def median_range_m(mode_name):
        sim = SimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0,
            )
            assert drv.connect("sim", 0, False)
            drv.detect_and_init_strategy()
            assert drv.start_motor(mode_name, 600)
            got = None
            deadline = time.monotonic() + 15
            while got is None and time.monotonic() < deadline:
                got = drv.grab_scan_host(2.0)
            drv.stop_motor()
            drv.disconnect()
        finally:
            sim.stop()
        assert got is not None
        d = got[0]["dist_q2"]
        d = d[d > 0]
        return float(np.median(d)) / 4000.0

    std = median_range_m("Standard")
    ultra = median_range_m("UltraBoost")
    assert abs(ultra - std) / std < 0.05, (std, ultra)


class TestReplayRawFused:
    """replay_raw_fused: raw capture bytes -> filtered scans on device
    via the T-tick super-step drain, against the host decode ->
    replay_through_chain golden path (the acceptance contract: same
    range images, same final filter state, <= ceil(ticks/T)
    dispatches)."""

    def _params(self):
        from rplidar_ros2_driver_tpu.core.config import DriverParams

        return DriverParams(
            filter_backend="cpu",
            filter_chain=("clip", "median", "voxel"),
            filter_window=4,
            voxel_grid_size=32,
        )

    @pytest.mark.parametrize("mode_name", ["DenseBoost", "Sensitivity"])
    def test_matches_host_replay_path(self, tmp_path, mode_name):
        """Dense (unpaired) and express (prev-frame-paired) captures:
        identical range images and final FilterState, in the promised
        dispatch budget."""
        import math

        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
        from rplidar_ros2_driver_tpu.replay import (
            replay_raw_fused,
            replay_through_chain,
        )

        path = str(tmp_path / f"{mode_name}.rplr")
        sim = SimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0,
            )
            assert drv.connect("sim", 0, False)
            drv.detect_and_init_strategy()
            drv.start_recording(path)
            assert drv.start_motor(mode_name, 600)
            got = 0
            deadline = time.monotonic() + 20
            while got < 3 and time.monotonic() < deadline:
                if drv.grab_scan_host(2.0) is not None:
                    got += 1
            assert drv.stop_recording() > 0
            drv.stop_motor()
            drv.disconnect()
        finally:
            sim.stop()

        params = self._params()
        revs = decode_recording(path).revolutions()
        assert revs
        ranges_h, state_h = replay_through_chain(
            revs, params, beams=256, capacity=4096
        )
        ranges_f, state_f, stats = replay_raw_fused(
            path, params, beams=256, capacity=4096,
            frames_per_tick=8, super_ticks=4,
        )
        np.testing.assert_array_equal(ranges_f, ranges_h)
        np.testing.assert_array_equal(
            np.asarray(state_f.voxel_acc), np.asarray(state_h.voxel_acc)
        )
        np.testing.assert_array_equal(
            np.asarray(state_f.range_window),
            np.asarray(state_h.range_window),
        )
        # the acceptance budget, and the super path actually engaged
        assert stats["dispatches"] <= math.ceil(stats["ticks"] / 4)
        assert stats["ticks"] > 1 and stats["super_dispatches"] >= 1
        assert stats["scans"] == ranges_h.shape[0]

    def test_empty_capture(self, tmp_path):
        from rplidar_ros2_driver_tpu.replay import replay_raw_fused

        p = str(tmp_path / "empty.rplr")
        with FrameRecorder(p):
            pass
        ranges, state, stats = replay_raw_fused(p, self._params(), beams=256)
        assert ranges.shape == (0, 256)
        assert stats["dispatches"] == 0 and stats["scans"] == 0

    def test_max_revs_drop_raises(self, tmp_path):
        """A frames_per_tick/max_revs pairing that would silently drop
        revolutions must raise instead (the parity contract)."""
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
        from rplidar_ros2_driver_tpu.replay import replay_raw_fused

        path = str(tmp_path / "c.rplr")
        sim = SimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0,
            )
            assert drv.connect("sim", 0, False)
            drv.detect_and_init_strategy()
            drv.start_recording(path)
            assert drv.start_motor("DenseBoost", 600)
            got = 0
            deadline = time.monotonic() + 20
            while got < 4 and time.monotonic() < deadline:
                if drv.grab_scan_host(2.0) is not None:
                    got += 1
            drv.stop_recording()
            drv.stop_motor()
            drv.disconnect()
        finally:
            sim.stop()
        with pytest.raises(ValueError, match="max_revs"):
            # the whole capture in one tick, one completion slot
            replay_raw_fused(
                path, self._params(), beams=256,
                frames_per_tick=4096, super_ticks=1, max_revs=1,
            )

    def test_cli_replay_fused(self, tmp_path):
        path, _ = _capture_from_sim(tmp_path, seconds=0.5)
        out = subprocess.run(
            [sys.executable, "-m", "rplidar_ros2_driver_tpu", "replay", path,
             "--cpu", "--fused"],
            capture_output=True,
            text=True,
            timeout=180,
        )
        assert out.returncode == 0, out.stderr
        assert "fused raw replay" in out.stdout
        assert "parity OK" in out.stdout
        assert "scans/s" in out.stdout


def test_replay_fleet_matches_per_stream_replay():
    """Fleet replay over the (stream, beam) mesh must reproduce each
    stream's single-device replay bit-for-bit: the beam partition is
    exact and the voxel all-reduce sums integers."""
    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh
    from rplidar_ros2_driver_tpu.replay import replay_fleet, replay_through_chain

    params = DriverParams(
        filter_window=4,
        filter_chain=("clip", "median", "voxel"),
        voxel_grid_size=16,
    )
    rng = np.random.default_rng(11)
    streams = []
    for s in range(4):
        revs = []
        for k in range(10):
            n = 60 + 4 * k + s
            revs.append({
                "angle_q14": ((np.arange(n) * 65536) // n).astype(np.int32),
                "dist_q2": (rng.uniform(0.3, 8.0, n) * 4000).astype(np.int32),
                "quality": np.full(n, 180, np.int32),
            })
        streams.append(revs)

    mesh = make_mesh(8, stream=2)
    ranges, state = replay_fleet(
        streams, params, mesh=mesh, beams=64, capacity=128, chunk=6
    )
    assert ranges.shape == (4, 10, 64)
    for s, revs in enumerate(streams):
        ref, ref_state = replay_through_chain(revs, params, beams=64, capacity=128, chunk=6)
        np.testing.assert_array_equal(ranges[s], ref)
        np.testing.assert_array_equal(
            np.asarray(state.voxel_acc[s]), np.asarray(ref_state.voxel_acc)
        )


def test_replay_fleet_default_mesh_awkward_beam_count():
    """The default mesh must shrink itself when no full-device split has
    a beam extent dividing cfg.beams (2 streams x 8 devices x 6 beams:
    gcd would pick beam=4, which does not divide 6 — the workable split
    is 6 devices as (stream=2, beam=3))."""
    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.replay import replay_fleet, replay_through_chain

    params = DriverParams(
        filter_window=4,
        filter_chain=("clip", "median", "voxel"),
        voxel_grid_size=16,
    )
    rng = np.random.default_rng(23)
    streams = []
    for s in range(2):
        revs = []
        for k in range(6):
            n = 40 + 3 * k + s
            revs.append({
                "angle_q14": ((np.arange(n) * 65536) // n).astype(np.int32),
                "dist_q2": (rng.uniform(0.3, 8.0, n) * 4000).astype(np.int32),
                "quality": np.full(n, 180, np.int32),
            })
        streams.append(revs)

    ranges, _ = replay_fleet(streams, params, beams=6, capacity=64, chunk=3)
    assert ranges.shape == (2, 6, 6)
    for s, revs in enumerate(streams):
        ref, _ = replay_through_chain(revs, params, beams=6, capacity=64, chunk=3)
        np.testing.assert_array_equal(ranges[s], ref)
