"""Live batched decode path (driver/decode.BatchScanDecoder).

Parity contract: streaming frames through the live decoder in arbitrary
chunk sizes must produce the exact node stream of the scalar golden
decoders (ops/unpack_ref.py) run frame-by-frame — same values, same order
— for all six wire formats, with the cross-run carries (previous frame,
dense sync edge, ultra-dense smoothing) handled at every chunk boundary.

Timestamp contract: every node is stamped ``cur_frame_rx − delay(idx)``
per the reference's per-sample delay model (protocol/timing.py), exact
through chunk boundaries and multi-revolution batches.

Throughput contract (VERDICT r1 #2): sustained live decode must beat the
S2 DenseBoost device rate (32 kSa/s) with >= 3x margin.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.driver.assembly import RawNodeHolder, ScanAssembler
from rplidar_ros2_driver_tpu.driver.decode import BatchScanDecoder
from rplidar_ros2_driver_tpu.ops import unpack_ref, wire
from rplidar_ros2_driver_tpu.protocol import crc as crcmod
from rplidar_ros2_driver_tpu.protocol.constants import Ans
from rplidar_ros2_driver_tpu.protocol.timing import (
    SAMPLES_PER_FRAME,
    TimingDesc,
    sample_delay_us,
)


def _rng():
    return np.random.default_rng(987)


def _angles(rng, m, step_q6=1200):
    inc = rng.integers(step_q6 // 2, step_q6 * 2, m)
    return (np.cumsum(inc) + rng.integers(0, 360 << 6)) % (360 << 6)


def _make_stream(ans_type: int, m: int, rng, syncs=(0,), corrupt=()):
    """Wire-format frame stream via ops/wire.py encoders."""
    frames = []
    if ans_type == Ans.MEASUREMENT:
        for i in range(m):
            frames.append(
                wire.encode_normal_node(
                    int(rng.integers(0, 360 << 6)),
                    int(rng.integers(0, 1 << 16)),
                    int(rng.integers(0, 64)),
                    syncbit=(i in syncs),
                )
            )
        return frames
    if ans_type == Ans.MEASUREMENT_HQ:
        for i in range(m):
            frames.append(
                wire.encode_hq_capsule(
                    rng.integers(0, 1 << 16, 96),
                    rng.integers(0, 1 << 18, 96),
                    rng.integers(0, 256, 96),
                    np.where(np.arange(96) == 0, int(i in syncs), 2),
                    timestamp=1000 * i,
                )
            )
        return frames
    starts = _angles(rng, m)
    for i in range(m):
        if ans_type == Ans.MEASUREMENT_CAPSULED:
            dist = rng.integers(0, 1 << 14, (16, 2)) << 2
            dist[rng.random((16, 2)) < 0.1] = 0
            fr = bytearray(
                wire.encode_capsule(
                    int(starts[i]), i in syncs, dist, rng.integers(0, 64, (16, 2))
                )
            )
        elif ans_type == Ans.MEASUREMENT_CAPSULED_ULTRA:
            fr = bytearray(
                wire.encode_ultra_capsule(
                    int(starts[i]),
                    i in syncs,
                    rng.integers(0, 4096, 32),
                    rng.integers(-512, 512, 32),
                    rng.integers(-512, 512, 32),
                )
            )
        elif ans_type == Ans.MEASUREMENT_DENSE_CAPSULED:
            fr = bytearray(
                wire.encode_dense_capsule(
                    int(starts[i]), i in syncs, rng.integers(0, 25000, 40)
                )
            )
        else:
            base = int(rng.integers(100, 2000))
            dmm = base + rng.integers(-2, 3, 64).cumsum() % 30000
            words = np.array(
                [
                    wire.ultra_dense_encode_sample(int(d), int(q))
                    for d, q in zip(dmm, rng.integers(0, 256, 64))
                ]
            )
            fr = bytearray(
                wire.encode_ultra_dense_capsule(int(starts[i]), i in syncs, words)
            )
        if i in corrupt:
            fr[20] ^= 0x3C
        frames.append(bytes(fr))
    return frames


def _scalar_nodes(ans_type: int, frames) -> list:
    """Expected flat node stream from the scalar golden decoders."""
    if ans_type == Ans.MEASUREMENT:
        return [n for f in frames if (n := unpack_ref.decode_normal_node(f))]
    if ans_type == Ans.MEASUREMENT_HQ:
        out = []
        for f in frames:
            nodes, _ts = unpack_ref.decode_hq_capsule(f)
            out.extend(nodes)
        return out
    dec = {
        Ans.MEASUREMENT_CAPSULED: unpack_ref.CapsuleDecoder,
        Ans.MEASUREMENT_CAPSULED_ULTRA: unpack_ref.UltraCapsuleDecoder,
        Ans.MEASUREMENT_DENSE_CAPSULED: unpack_ref.DenseCapsuleDecoder,
        Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED: unpack_ref.UltraDenseCapsuleDecoder,
    }[ans_type]()
    out = []
    for f in frames:
        nodes, _ = dec.decode(f)
        out.extend(nodes)
    return out


def _drain_live(ans_type: int, frames, chunks_rng, timing=None):
    """Feed frames through BatchScanDecoder in random chunk sizes; return
    the raw-holder node stream (every emitted node, in order)."""
    holder = RawNodeHolder(capacity=1 << 20)
    dec = BatchScanDecoder(ScanAssembler(), holder)
    if timing is not None:
        dec.timing = timing
    i = 0
    t = 1000.0
    while i < len(frames):
        k = int(chunks_rng.integers(1, 8))
        batch = []
        for f in frames[i : i + k]:
            t += 0.002
            batch.append((f, t))
        dec.on_measurement_batch(ans_type, batch)
        i += k
    got = holder.fetch()
    return dec, (np.zeros((0, 4), np.int32) if got is None else got)


ALL_FORMATS = sorted(SAMPLES_PER_FRAME, key=int)


class TestChunkedLiveParity:
    @pytest.mark.parametrize("ans", ALL_FORMATS)
    def test_matches_scalar_stream(self, ans):
        rng = _rng()
        frames = _make_stream(ans, 40, rng, syncs=(0, 17))
        expected = _scalar_nodes(ans, frames)
        _, got = _drain_live(ans, frames, _rng())
        assert len(got) == len(expected), (len(got), len(expected))
        for k, n in enumerate(expected):
            assert got[k, 0] == n.angle_q14, (k, got[k, 0], n.angle_q14)
            assert got[k, 1] == n.dist_q2, (k, got[k, 1], n.dist_q2)
            assert got[k, 2] == n.quality, k
            assert got[k, 3] == n.flag, (k, got[k, 3], n.flag)

    @pytest.mark.parametrize(
        "ans",
        [
            Ans.MEASUREMENT_CAPSULED,
            Ans.MEASUREMENT_CAPSULED_ULTRA,
        ],
    )
    def test_corruption_isolated_to_adjacent_pairs(self, ans):
        """A corrupt frame must drop exactly the pairs it touches — same
        as the scalar decoders — even when the corruption lands next to a
        chunk boundary."""
        rng = _rng()
        frames = _make_stream(ans, 30, rng, syncs=(0,), corrupt=(9, 10, 21))
        expected = _scalar_nodes(ans, frames)
        _, got = _drain_live(ans, frames, _rng())
        assert len(got) == len(expected)
        assert np.array_equal(got[:, 0], [n.angle_q14 for n in expected])
        assert np.array_equal(got[:, 1], [n.dist_q2 for n in expected])

    def test_chunk_boundaries_do_not_matter(self):
        """Same stream, three different chunkings -> identical node stream
        (carries are exact at every boundary)."""
        rng = _rng()
        ans = Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED
        frames = _make_stream(ans, 48, rng, syncs=(0, 20), corrupt=(13,))
        ref = None
        for seed in (1, 2, 3):
            _, got = _drain_live(ans, frames, np.random.default_rng(seed))
            if ref is None:
                ref = got
            else:
                assert np.array_equal(ref, got)

    def test_ans_type_change_resets_stream_state(self):
        holder = RawNodeHolder(capacity=1 << 20)
        dec = BatchScanDecoder(ScanAssembler(), holder)
        rng = _rng()
        caps = _make_stream(Ans.MEASUREMENT_CAPSULED, 6, rng)
        dec.on_measurement_batch(
            Ans.MEASUREMENT_CAPSULED, [(f, 1.0) for f in caps]
        )
        assert dec._prev is not None
        dense = _make_stream(Ans.MEASUREMENT_DENSE_CAPSULED, 6, rng)
        dec.on_measurement_batch(
            Ans.MEASUREMENT_DENSE_CAPSULED, [(f, 2.0) for f in dense]
        )
        # the capsule carry must not leak into the dense stream: output
        # equals a fresh dense-only scalar decode
        expected_dense = _scalar_nodes(Ans.MEASUREMENT_DENSE_CAPSULED, dense)
        got = holder.fetch()
        # first run produced capsule nodes; compare the dense tail
        tail = got[len(got) - len(expected_dense) :]
        assert np.array_equal(tail[:, 0], [n.angle_q14 for n in expected_dense])


class TestLiveTimestamps:
    def test_per_node_backdating_matches_delay_model(self):
        """Nodes of pair (prev, cur) are stamped cur_rx − delay(idx)."""
        ans = Ans.MEASUREMENT_CAPSULED
        rng = _rng()
        frames = _make_stream(ans, 2, rng, syncs=())
        pushed = {}

        class Tap(ScanAssembler):
            def push_nodes(self, angle, dist, quality, flag, ts=None):
                pushed["ts"] = np.asarray(ts)
                pushed["n"] = len(angle)
                return 0

        dec = BatchScanDecoder(Tap())
        timing = TimingDesc(sample_duration_us=65.0, native_baudrate=256000)
        dec.timing = timing
        rx = [100.0, 100.005]
        dec.on_measurement_batch(ans, list(zip(frames, rx)))
        assert pushed["n"] == 32
        for idx in range(32):
            expect = rx[1] - 1e-6 * sample_delay_us(ans, timing, idx)
            assert pushed["ts"][idx] == pytest.approx(expect, abs=1e-9)

    def test_hq_nodes_share_frame_stamp(self):
        """HQ/normal formats have no grouping delay: one stamp per frame."""
        ans = Ans.MEASUREMENT_HQ
        frames = _make_stream(ans, 3, _rng())
        seen = []

        class Tap(ScanAssembler):
            def push_nodes(self, angle, dist, quality, flag, ts=None):
                seen.append(np.asarray(ts))
                return 0

        dec = BatchScanDecoder(Tap())
        timing = TimingDesc(sample_duration_us=32.0, native_baudrate=1_000_000)
        dec.timing = timing
        rx = [50.0, 50.01, 50.02]
        dec.on_measurement_batch(ans, list(zip(frames, rx)))
        ts = np.concatenate(seen)
        assert ts.shape == (3 * 96,)
        d0 = 1e-6 * sample_delay_us(ans, timing, 0)
        for i in range(3):
            frame_ts = ts[i * 96 : (i + 1) * 96]
            assert np.all(frame_ts == frame_ts[0])
            assert frame_ts[0] == pytest.approx(rx[i] - d0, abs=1e-9)

    def test_multi_revolution_batch_gets_distinct_boundaries(self):
        """ADVICE r1: two syncs inside one pushed batch must yield two
        revolutions with their own begin timestamps and nonzero duration."""
        asm = ScanAssembler()
        n = 300
        flag = np.full(n, 2, np.int32)
        flag[0] = flag[100] = flag[200] = 1
        ts = 10.0 + 0.001 * np.arange(n)
        asm.push_nodes(
            ((np.arange(n) * 65536) // n).astype(np.int32),
            np.full(n, 4000, np.int32),
            np.full(n, 200, np.int32),
            flag,
            ts=ts,
        )
        got1 = asm.wait_and_grab_with_timestamp(0.1)
        assert got1 is not None
        _, ts0, dur = got1
        # newest-wins double buffer: the pending scan is the SECOND
        # revolution (100..200), with its own boundary stamps
        assert ts0 == pytest.approx(10.0 + 0.1)
        assert dur == pytest.approx(0.1)
        assert asm.scans_completed == 2
        assert asm.scans_dropped == 1


class TestLiveDecodeRate:
    def test_sustained_rate_beats_denseboost_3x(self):
        """VERDICT r1 done-criterion: live decode >= 3 x 32 kSa/s."""
        ans = Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED
        rng = _rng()
        frames = _make_stream(ans, 512, rng, syncs=(0,))
        holder = RawNodeHolder(capacity=1 << 22)
        asm = ScanAssembler()
        dec = BatchScanDecoder(asm, holder)
        dec.precompile(ans)
        # feed in engine-sized runs (16 frames/run), timing like the pump
        run = 16
        t0 = time.perf_counter()
        t = 0.0
        for i in range(0, len(frames), run):
            batch = [(f, t + k * 0.002) for k, f in enumerate(frames[i : i + run])]
            t += run * 0.002
            dec.on_measurement_batch(ans, batch)
        dt = time.perf_counter() - t0
        rate = dec.nodes_decoded / dt
        assert dec.nodes_decoded > 30000
        assert rate >= 3 * 32000, f"live decode {rate:.0f} Sa/s < 96 kSa/s"


class TestOversizedRuns:
    @pytest.mark.parametrize(
        "ans", [Ans.MEASUREMENT, Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED]
    )
    def test_runs_larger_than_biggest_bucket_decode_exactly(self, ans):
        """A run longer than _BUCKETS[-1] must decode in slices (carries
        make slicing exact), not crash or drop the run."""
        rng = _rng()
        frames = _make_stream(ans, 150, rng, syncs=(0, 70))
        expected = _scalar_nodes(ans, frames)
        holder = RawNodeHolder(capacity=1 << 20)
        dec = BatchScanDecoder(ScanAssembler(), holder)
        # ONE oversized delivery
        dec.on_measurement_batch(ans, [(f, 1.0 + 0.002 * i) for i, f in enumerate(frames)])
        got = holder.fetch()
        assert got is not None and len(got) == len(expected)
        assert np.array_equal(got[:, 0], [n.angle_q14 for n in expected])
        assert np.array_equal(got[:, 1], [n.dist_q2 for n in expected])


class TestRxThreadTimestamps:
    def test_native_rx_timestamps_preserve_interframe_spacing(self):
        """Frames queued by the native rx thread carry arrival stamps taken
        in the rx thread: draining them later (all at once) must still show
        the true spacing, not drain-time compression."""
        import socket
        import struct
        import threading
        import time as _time

        from rplidar_ros2_driver_tpu.native.runtime import NativeChannel, NativeTransceiver

        hdr = b"\xa5\x5a" + struct.pack("<I", (5 & 0x3FFFFFFF) | (0x1 << 30)) + b"\x81"
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def server():
            conn, _ = srv.accept()
            with conn:
                conn.sendall(hdr)
                for i in range(4):
                    conn.sendall(bytes([i]) * 5)  # one 5-byte payload
                    _time.sleep(0.05)
                _time.sleep(0.3)

        t = threading.Thread(target=server, daemon=True)
        t.start()
        ch = NativeChannel("tcp", "127.0.0.1", port=port)
        tx = NativeTransceiver(ch)
        assert tx.start()
        _time.sleep(0.35)  # let all 4 frames arrive BEFORE we drain
        got = []
        while len(got) < 4:
            m = tx.wait_message_ts(timeout_ms=2000)
            assert m is not None
            got.append(m)
        tx.stop()
        srv.close()
        t.join(3)
        stamps = [ts for (_a, _p, _l, ts) in got]
        gaps = np.diff(stamps)
        # drained in one go, but the stamps keep the ~50 ms producer spacing
        assert np.all(gaps > 0.02), gaps
        # and they are CLOCK_MONOTONIC (comparable with time.monotonic())
        assert abs(stamps[-1] - _time.monotonic()) < 5.0


class TestHqCrcGate:
    def test_bad_crc_frame_dropped(self):
        frames = _make_stream(Ans.MEASUREMENT_HQ, 2, _rng())
        bad = bytearray(frames[1])
        bad[50] ^= 0xFF
        assert crcmod.crc32_padded(bytes(bad[:-4])) != int.from_bytes(bad[-4:], "little")
        _, got = _drain_live(Ans.MEASUREMENT_HQ, [frames[0], bytes(bad)], _rng())
        assert len(got) == 96  # only the intact frame's nodes
