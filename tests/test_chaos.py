"""Chaos-hardened fleet: deterministic fault injection, per-stream
health FSM, and quarantine-based graceful degradation.

The acceptance contract this suite pins:

  * **Chaos parity** — under an identical seeded fault schedule
    (driver/chaos.py), the host-golden decode path and the fused device
    path produce bit-exact scans AND maps, across a full quarantine ->
    recover -> rejoin cycle with the stream's filter+map state restored
    from its per-stream checkpoint.
  * **Zero recompiles / zero implicit transfers** — the whole cycle
    (fault onset, quarantine snapshot, masked ticks, probe+release,
    checkpoint restore, rejoin) runs inside utils/guards.steady_state:
    quarantined streams ride the EXISTING idle padding lanes, so the
    one compiled program per fleet tick never changes shape.
  * **Fault isolation** — healthy streams' outputs are bit-exact
    identical whether or not a neighbor is faulting/quarantined.
  * The health FSM itself: transition walk, backoff escalation, probe
    gating, starvation detection (unit tests on driver/health.py).
  * The injection machinery: schedule determinism, transport-vs-frame
    applier equivalence, and the emulated firmware's fault mode
    surviving the full driver stack (driver/chaos.py, sim_device).
"""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.driver.assembly import ScanAssembler
from rplidar_ros2_driver_tpu.driver.chaos import (
    ChaosConfig,
    ChaosSchedule,
    ChaosStream,
    ChaosTransport,
    chaos_ticks,
)
from rplidar_ros2_driver_tpu.driver.decode import BatchScanDecoder
from rplidar_ros2_driver_tpu.driver.health import (
    BackoffPolicy,
    FleetHealth,
    HealthConfig,
    StreamHealth,
    StreamState,
)
from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest
from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain
from rplidar_ros2_driver_tpu.mapping.mapper import FleetMapper
from rplidar_ros2_driver_tpu.ops import wire
from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
from rplidar_ros2_driver_tpu.protocol.constants import Ans
from rplidar_ros2_driver_tpu.utils import guards

from test_fused_ingest import BEAMS, _params

DENSE = int(Ans.MEASUREMENT_DENSE_CAPSULED)

OUT_FIELDS = ("ranges", "intensities", "points_xy", "point_mask", "voxel")


# ---------------------------------------------------------------------------
# fixtures: a deterministic DenseBoost fleet tick stream
# ---------------------------------------------------------------------------


def _denseboost_frames(revs: int, ppr: int = 400) -> list:
    frames, idx, first = [], 0, True
    while idx < revs * ppr:
        theta = 360.0 * (idx % ppr) / ppr
        pts = (np.arange(40) + idx) % ppr
        d = 2000.0 + 500.0 * np.sin(2 * np.pi * pts / ppr)
        frames.append(wire.encode_dense_capsule(
            int(theta * 64) & 0x7FFF, first, d.astype(int)
        ))
        idx += 40
        first = False
    return frames


def _fleet_ticks(streams: int, revs: int, per_tick: int = 5) -> list:
    """Deterministic lockstep ticks (every stream streams every tick —
    masking decisions, not arrival randomness, are under test here)."""
    frames = _denseboost_frames(revs)
    ticks = []
    t = [100.0 + 7.0 * s for s in range(streams)]
    for i in range(0, len(frames), per_tick):
        tick = []
        for s in range(streams):
            batch = []
            for f in frames[i : i + per_tick]:
                t[s] += 1.25e-3
                batch.append((f, t[s]))
            tick.append((DENSE, batch))
        ticks.append(tick)
    return ticks


def _map_params(**over):
    base = dict(
        map_enable=True, map_grid=64, map_cell_m=0.1,
    )
    base.update(over)
    return _params(**base)


def _host_replay(ticks, mask_log, rejoins, streams, params):
    """The golden reference for the masked fleet: per stream, an
    independent decoder+assembler+chain over EXACTLY the bytes the
    fused engine was allowed to see (the recorded admitted-mask log),
    with the decoder+assembler reset at each rejoin tick (the fused
    path's decode-carry reset on checkpoint restore) and the chain —
    like the restored filter window — carried straight through.  A
    per-stream host mapper consumes the newest output per tick, like
    the service's mapper seam.  Returns (per_tick outputs, mappers)."""
    per_tick = [[None] * streams for _ in ticks]
    mappers = [FleetMapper(params, 1, beams=BEAMS) for _ in range(streams)]
    for i in range(streams):
        completed: list = []
        asm = ScanAssembler(
            on_complete=lambda sc, c=completed: c.append(dict(sc))
        )
        dec = BatchScanDecoder(asm)
        chain = ScanFilterChain(params, beams=BEAMS, warmup=False)
        for t, tick in enumerate(ticks):
            if t in rejoins.get(i, ()):
                dec.reset()
                asm.reset()
            if not mask_log[t][i]:
                continue
            item = tick[i]
            n0 = len(completed)
            if item:
                dec.on_measurement_batch(item[0], list(item[1]))
            outs = [
                chain.process_raw(
                    sc["angle_q14"], sc["dist_q2"], sc["quality"], sc["flag"]
                )
                for sc in completed[n0:]
            ]
            if outs:
                per_tick[t][i] = outs[-1]
                mappers[i].submit([outs[-1]])
    return per_tick, mappers


# ---------------------------------------------------------------------------
# the tier-1 acceptance test
# ---------------------------------------------------------------------------


class TestChaosFleetParity:
    def test_quarantine_cycle_bit_exact_zero_recompiles(self):
        """Fleet of 4, stream 1 fed a seeded corruption burst: the
        stream must walk HEALTHY -> SUSPECT -> QUARANTINED ->
        RECOVERING -> HEALTHY with its filter window and map restored
        from the quarantine checkpoint, the whole cycle must run with
        zero recompiles and zero implicit transfers, healthy neighbors
        must never leave HEALTHY, and every published output and final
        map must be bit-exact against the host-golden replay of the
        identical masked byte stream."""
        streams, revs = 4, 10
        ticks = _fleet_ticks(streams, revs)
        # stream 1: clean for 2 revolutions, then a 20-frame burst of
        # heavy corruption/truncation, clean afterwards — deterministic
        chaos_cfg = ChaosConfig(
            seed=3, start_frame=20, stop_frame=40,
            corrupt_rate=0.9, truncate_rate=0.5,
        )
        cticks = chaos_ticks(ticks, {1: chaos_cfg})

        params = _map_params(fleet_ingest_backend="fused",
                             map_backend="fused")
        svc = ShardedFilterService(
            params, streams, beams=BEAMS, fleet_ingest_buckets=(8,)
        )
        svc._ensure_byte_ingest()
        svc.fleet_ingest.precompile([DENSE])
        svc.attach_mapper()
        svc.mapper.precompile()
        fake = {"now": 0.0}
        health = FleetHealth(
            streams,
            HealthConfig(
                window_ticks=3, corrupt_ratio=0.5, starvation_ticks=4,
                suspect_ticks=2, probation_ticks=2,
                backoff_base_s=0.4, backoff_jitter=0.0, seed=5,
            ),
            clock=lambda: fake["now"],
            probes={1: lambda: 0},  # GET_DEVICE_HEALTH: OK
            record_masks=True,
        )
        svc.attach_health(health)

        outs_log = []
        warm = 3  # clean warmup ticks (compiles + window fill)
        for tick in cticks[:warm]:
            outs_log.append([o for o in svc.submit_bytes(tick)])
            fake["now"] += 0.1
        with guards.steady_state(tag="chaos quarantine cycle"):
            for tick in cticks[warm:]:
                outs_log.append([o for o in svc.submit_bytes(tick)])
                fake["now"] += 0.1

        # the FSM walked the full cycle, and only on the faulty stream
        walk = [(s, old, new) for (_t, s, old, new) in health.events]
        assert (1, "healthy", "suspect") in walk
        assert (1, "suspect", "quarantined") in walk
        assert (1, "quarantined", "recovering") in walk
        assert (1, "recovering", "healthy") in walk
        assert all(s == 1 for (s, _o, _n) in walk)
        assert svc.quarantines == 1 and svc.rejoins == 1
        assert not svc.stream_checkpoints  # consumed at rejoin
        masked_ticks = sum(1 for m in health.mask_log if not m[1])
        assert masked_ticks >= 1  # the quarantine actually masked traffic

        # host-golden replay of the identical masked stream
        rejoins = {
            s: {t for (t, s2, _o, new) in health.events
                if s2 == s and new == "recovering"}
            for s in range(streams)
        }
        host_params = _map_params(map_backend="host")
        per_tick, host_mappers = _host_replay(
            cticks, health.mask_log, rejoins, streams, host_params
        )
        published = 0
        for t, row in enumerate(outs_log):
            for i in range(streams):
                h, f = per_tick[t][i], row[i]
                assert (h is None) == (f is None), (t, i)
                if h is None:
                    continue
                published += 1
                for field in OUT_FIELDS:
                    assert np.array_equal(
                        np.asarray(getattr(h, field)),
                        np.asarray(getattr(f, field)),
                    ), (t, i, field)
        assert published >= 2 * streams  # real coverage, not idle ticks

        # maps: the fused fleet's final per-stream MapState rows are
        # bit-exact vs the per-stream host mappers — including stream
        # 1's, whose map crossed the quarantine checkpoint round trip
        for i in range(streams):
            fused_row = svc.mapper.snapshot_stream(i)
            host_row = host_mappers[i].snapshot_stream(0)
            for k in ("log_odds", "pose", "origin_xy", "revision"):
                assert np.array_equal(fused_row[k], host_row[k]), (i, k)

    def test_fault_isolation_healthy_streams_unchanged(self):
        """Healthy streams' outputs are byte-for-byte identical whether
        a neighbor is clean or quarantined mid-run — per-stream state
        isolation at the engine level plus idle-lane masking at the
        service level."""
        streams, revs = 4, 6
        ticks = _fleet_ticks(streams, revs)
        chaos_cfg = ChaosConfig(
            seed=11, start_frame=10, stop_frame=30,
            corrupt_rate=0.9, truncate_rate=0.5,
        )

        def run(with_fault: bool):
            use = chaos_ticks(ticks, {1: chaos_cfg}) if with_fault else ticks
            svc = ShardedFilterService(
                _params(fleet_ingest_backend="fused"), streams,
                beams=BEAMS, fleet_ingest_buckets=(8,),
            )
            svc._ensure_byte_ingest()
            svc.fleet_ingest.precompile([DENSE])
            fake = {"now": 0.0}
            svc.attach_health(FleetHealth(
                streams,
                HealthConfig(window_ticks=3, corrupt_ratio=0.5,
                             starvation_ticks=4, suspect_ticks=2,
                             probation_ticks=2, backoff_base_s=0.4,
                             backoff_jitter=0.0),
                clock=lambda: fake["now"],
            ))
            outs = [[] for _ in range(streams)]
            for tick in use:
                for i, o in enumerate(svc.submit_bytes(tick)):
                    if o is not None:
                        outs[i].append(np.asarray(o.ranges).copy())
                fake["now"] += 0.1
            return outs, svc

        clean, _ = run(False)
        faulty, svc = run(True)
        assert svc.quarantines >= 1
        for i in (0, 2, 3):  # the healthy neighbors
            assert len(clean[i]) == len(faulty[i]) >= 1
            for a, b in zip(clean[i], faulty[i]):
                assert np.array_equal(a, b)
        # the faulty stream lost revolutions to masking, by design
        assert len(faulty[1]) < len(clean[1])


# ---------------------------------------------------------------------------
# per-stream checkpoint surfaces
# ---------------------------------------------------------------------------


class TestStreamCheckpoints:
    def test_ingest_restore_stream_rolls_back_one_lane(self):
        """restore_stream reinstalls the snapshotted filter window into
        ONE lane (decode carries reset for the mid-capsule re-entry)
        while every other lane's advanced state is untouched."""
        streams = 3
        ticks = _fleet_ticks(streams, 8)
        eng = FleetFusedIngest(
            _params(), streams, beams=BEAMS, buckets=(8,), max_revs=6
        )
        eng.precompile([DENSE] * streams)
        cut = len(ticks) // 2
        for tick in ticks[:cut]:
            eng.submit(tick)
        snap = eng.snapshot_stream(1)
        full_mid = eng.snapshot()
        for tick in ticks[cut:]:
            eng.submit(tick)
        full_end = eng.snapshot()
        # states moved after the snapshot point
        assert not np.array_equal(
            full_mid["filter.range_window"][1],
            full_end["filter.range_window"][1],
        )
        assert eng.restore_stream(1, snap)
        full_after = eng.snapshot()
        # lane 1: filter window rolled back to the snapshot
        assert np.array_equal(
            full_after["filter.range_window"][1],
            full_mid["filter.range_window"][1],
        )
        # lanes 0/2: end-state untouched
        for i in (0, 2):
            assert np.array_equal(
                full_after["filter.range_window"][i],
                full_end["filter.range_window"][i],
            )
        # the rejoin resets decode carries for the restored lane
        assert eng._reset_next[1] and not eng._reset_next[0]

    def test_ingest_restore_stream_rejects_mismatch(self):
        eng = FleetFusedIngest(_params(), 2, beams=BEAMS, buckets=(4,))
        snap = eng.snapshot_stream(0)
        bad = dict(snap)
        bad["version"] = np.asarray(99, np.int32)
        assert not eng.restore_stream(0, bad)
        other = FleetFusedIngest(
            _params(filter_window=8), 2, beams=BEAMS, buckets=(4,)
        )
        assert not other.restore_stream(0, snap)  # window geometry moved
        with pytest.raises(IndexError):
            eng.restore_stream(7, snap)

    @pytest.mark.parametrize("backend", ["host", "fused"])
    def test_mapper_stream_roundtrip(self, backend):
        p = _map_params(map_backend=backend)
        m = FleetMapper(p, 3, beams=64)
        rng = np.random.default_rng(0)
        pts = rng.uniform(-2, 2, (3, 64, 2)).astype(np.float32)
        masks = np.ones((3, 64), bool)
        m.submit_points(pts, masks, np.ones((3,), np.int32))
        snap = m.snapshot_stream(1)
        m.submit_points(pts + 0.5, masks, np.ones((3,), np.int32))
        after = m.snapshot_stream(1)
        assert not np.array_equal(snap["log_odds"], after["log_odds"])
        assert m.restore_stream(1, snap)
        back = m.snapshot_stream(1)
        for k in ("log_odds", "pose", "origin_xy", "revision"):
            assert np.array_equal(back[k], snap[k]), k
        # neighbors keep their advanced maps
        assert m.snapshot_stream(0)["revision"] == 2
        bad = dict(snap)
        bad["version"] = np.asarray(-5, np.int32)
        assert not m.restore_stream(1, bad)


# ---------------------------------------------------------------------------
# health FSM units
# ---------------------------------------------------------------------------


class TestBackoffPolicy:
    def test_caps_and_escalates(self):
        bp = BackoffPolicy(0.5, 4.0, jitter=0.0, seed=1)
        assert [bp.next_delay() for _ in range(6)] == [
            0.5, 1.0, 2.0, 4.0, 4.0, 4.0
        ]
        bp.reset()
        assert bp.attempt == 0 and bp.next_delay() == 0.5

    def test_jitter_is_seed_deterministic_and_bounded(self):
        a = BackoffPolicy(1.0, 8.0, jitter=0.25, seed=42)
        b = BackoffPolicy(1.0, 8.0, jitter=0.25, seed=42)
        da = [a.next_delay() for _ in range(5)]
        assert da == [b.next_delay() for _ in range(5)]
        for k, d in enumerate(da):
            raw = min(1.0 * 2 ** k, 8.0)
            assert raw <= d <= raw * 1.25

    def test_rejects_bad_config(self):
        with pytest.raises(ValueError):
            BackoffPolicy(0.0, 1.0)
        with pytest.raises(ValueError):
            BackoffPolicy(2.0, 1.0)
        with pytest.raises(ValueError):
            BackoffPolicy(1.0, 2.0, jitter=1.5)

    def test_no_overflow_after_thousands_of_attempts(self):
        # regression: 2.0**1024 overflows a Python float; a device dead
        # for hours walks the attempt counter that far, and the retry
        # loop must keep pacing at the cap instead of raising
        bp = BackoffPolicy(0.5, 30.0, jitter=0.0)
        for _ in range(3000):
            d = bp.next_delay()
        assert d == 30.0 and bp.attempt == 3000

    def test_health_config_validates_domain(self):
        with pytest.raises(ValueError):
            HealthConfig(window_ticks=0)
        with pytest.raises(ValueError):
            HealthConfig(corrupt_ratio=1.5)
        with pytest.raises(ValueError):
            HealthConfig(backoff_base_s=2.0, backoff_max_s=1.0)


class TestStreamHealthFsm:
    def _cfg(self, **over):
        base = dict(
            window_ticks=4, corrupt_ratio=0.5, starvation_ticks=3,
            suspect_ticks=2, probation_ticks=2, backoff_base_s=1.0,
            backoff_jitter=0.0,
        )
        base.update(over)
        return HealthConfig(**base)

    def test_corruption_walk_and_recovery(self):
        t = {"now": 0.0}
        h = StreamHealth(self._cfg(), clock=lambda: t["now"],
                         probe=lambda: 0)
        for _ in range(3):
            assert h.observe(4, 0, 1) is None
        trs = [h.observe(4, 4, 0) for _ in range(4)]
        seq = [tr for tr in trs if tr]
        assert seq[0] == (StreamState.HEALTHY, StreamState.SUSPECT)
        assert seq[1] == (StreamState.SUSPECT, StreamState.QUARANTINED)
        assert not h.admitted and h.quarantines == 1
        assert h.poll_release() is None  # backoff not expired
        t["now"] = 1.5
        assert h.poll_release() == (
            StreamState.QUARANTINED, StreamState.RECOVERING
        )
        assert h.observe(4, 0, 1) is None
        assert h.observe(4, 0, 1) == (
            StreamState.RECOVERING, StreamState.HEALTHY
        )
        assert h.recoveries == 1 and h.backoff.attempt == 0

    def test_suspect_clears_on_probation(self):
        h = StreamHealth(self._cfg(suspect_ticks=5), clock=lambda: 0.0)
        h.observe(4, 0, 1)
        for _ in range(3):
            h.observe(4, 4, 0)
        assert h.state is StreamState.SUSPECT
        trs = [h.observe(4, 0, 1) for _ in range(4)]
        assert (StreamState.SUSPECT, StreamState.HEALTHY) in [
            tr for tr in trs if tr
        ]

    def test_starvation_of_streaming_stream(self):
        h = StreamHealth(self._cfg(starvation_ticks=2), clock=lambda: 0.0)
        h.observe(4, 0, 1)  # streamed once
        trs = [h.observe(0, 0, 0) for _ in range(6)]  # then silence
        assert any(
            tr and tr[1] is StreamState.QUARANTINED for tr in trs
        )
        assert "starved" in h.last_reason

    def test_idle_stream_is_not_sick(self):
        h = StreamHealth(self._cfg(starvation_ticks=1), clock=lambda: 0.0)
        for _ in range(10):
            assert h.observe(0, 0, 0) is None  # never streamed: idle
        assert h.state is StreamState.HEALTHY

    def test_probe_failure_rearms_escalated_backoff(self):
        t = {"now": 0.0}
        h = StreamHealth(
            self._cfg(window_ticks=2, suspect_ticks=1, starvation_ticks=1),
            clock=lambda: t["now"], probe=lambda: 2,  # ERROR
        )
        h.observe(4, 0, 1)
        for _ in range(4):
            h.observe(4, 4, 0)
        assert h.state is StreamState.QUARANTINED
        first_release = h.release_at
        t["now"] = first_release + 0.1
        assert h.poll_release() is None
        assert h.reconnect_failures == 1 and h.backoff.attempt == 2
        assert h.release_at > first_release
        h.probe = lambda: True
        t["now"] = h.release_at + 0.1
        assert h.poll_release() is not None

    def test_recovering_relapse_requarantines(self):
        t = {"now": 0.0}
        h = StreamHealth(
            self._cfg(window_ticks=2, suspect_ticks=1, starvation_ticks=9),
            clock=lambda: t["now"],
        )
        h.observe(4, 0, 1)
        for _ in range(3):
            h.observe(4, 4, 0)
        assert h.state is StreamState.QUARANTINED
        t["now"] = h.release_at + 0.1
        h.poll_release()
        assert h.state is StreamState.RECOVERING
        tr = h.observe(4, 4, 0)  # still corrupt: relapse
        assert tr == (StreamState.RECOVERING, StreamState.QUARANTINED)
        assert h.backoff.attempt >= 2  # escalated, not reset


# ---------------------------------------------------------------------------
# injection machinery
# ---------------------------------------------------------------------------


class TestChaosDeterminism:
    def test_schedule_is_pure_and_seeded(self):
        cfg = ChaosConfig(seed=7, corrupt_rate=0.4, truncate_rate=0.2,
                          drop_rate=0.1)
        a, b = ChaosSchedule(cfg), ChaosSchedule(cfg)
        assert [a.plan(i) for i in range(300)] == [
            b.plan(i) for i in range(300)
        ]
        other = ChaosSchedule(ChaosConfig(seed=8, corrupt_rate=0.4,
                                          truncate_rate=0.2, drop_rate=0.1))
        assert [a.plan(i) for i in range(300)] != [
            other.plan(i) for i in range(300)
        ]

    def test_appliers_agree_regardless_of_chunking(self):
        cfg = ChaosConfig(seed=5, corrupt_rate=0.5, truncate_rate=0.3)
        frames = [(bytes([i % 256] * 84), 0.1 * i) for i in range(60)]
        whole = ChaosStream(cfg).apply_run(list(frames))
        chunked = ChaosStream(cfg)
        got = []
        for k in range(0, 60, 7):
            got.extend(chunked.apply_run(list(frames[k : k + 7])))
        assert whole == got

    def test_window_and_stall(self):
        cfg = ChaosConfig(seed=1, start_frame=10, stop_frame=20,
                          corrupt_rate=1.0)
        s = ChaosSchedule(cfg)
        assert all(s.plan(i) == "pass" for i in range(10))
        assert all(s.plan(i) == "corrupt" for i in range(10, 20))
        assert all(s.plan(i) == "pass" for i in range(20, 30))
        st = ChaosSchedule(ChaosConfig(stall_period=10, stall_frames=3))
        kinds = [st.plan(i) for i in range(20)]
        assert kinds[:3] == ["stall"] * 3 and kinds[3:10] == ["pass"] * 7
        assert kinds[10:13] == ["stall"] * 3

    def test_invalid_config_rejected(self):
        with pytest.raises(ValueError):
            ChaosConfig(corrupt_rate=1.5)
        with pytest.raises(ValueError):
            ChaosConfig(stall_period=3, stall_frames=3)


class _ScriptedTx:
    """Minimal TransceiverLike feeding a fixed measurement sequence."""

    def __init__(self, frames):
        self.queue = [(DENSE, f, True) for f in frames]
        self.had_error = False

    def start(self):
        return True

    def stop(self):
        pass

    def send(self, packet):
        return True

    def reset_decoder(self):
        pass

    def wait_message(self, timeout_ms=1000):
        return self.queue.pop(0) if self.queue else None


class TestChaosTransport:
    def test_transport_matches_frame_applier(self):
        """The transport wrapper and the frame-run applier built from
        one config deliver the identical surviving byte sequence — the
        property that lets fleet harnesses corrupt once and feed both
        ingest backends."""
        cfg = ChaosConfig(seed=9, corrupt_rate=0.5, truncate_rate=0.2,
                          drop_rate=0.2)
        frames = [bytes([i % 256] * 84) for i in range(50)]
        ref = ChaosStream(cfg).apply_run([(f, 0.0) for f in frames])
        tx = ChaosTransport(_ScriptedTx(frames), cfg)
        got = []
        while True:
            m = tx.wait_message()
            if m is None and not tx._tx.queue:
                break
            if m is not None:
                got.append(m[1])
        assert got == [f for f, _ in ref]

    def test_request_plane_passes_clean(self):
        cfg = ChaosConfig(seed=1, corrupt_rate=1.0)
        tx = _ScriptedTx([])
        tx.queue = [(int(Ans.DEVINFO), b"\x01" * 20, False)]
        ct = ChaosTransport(tx, cfg)
        assert ct.wait_message() == (int(Ans.DEVINFO), b"\x01" * 20, False)

    def test_disconnect_raises_channel_error(self):
        from rplidar_ros2_driver_tpu.native.runtime import ChannelError

        cfg = ChaosConfig(disconnect_frames=(2,))
        ct = ChaosTransport(
            _ScriptedTx([bytes(84)] * 5), cfg
        )
        assert ct.wait_message() is not None
        assert ct.wait_message() is not None
        with pytest.raises(ChannelError):
            ct.wait_message()
        assert ct.had_error


class TestSimDeviceChaos:
    @pytest.mark.slow
    def test_driver_survives_corrupting_firmware(self):
        # slow-marked: the tier-1 budget twin is the fleet-level chaos
        # parity above (same corruption classes through the same
        # decoders); this one drives the FULL live stack (tcp
        # transport -> pump -> decoder resync -> assembler) and rides
        # the slow lane with the chaos soak
        """The emulated firmware mutates its own wire frames; the real
        driver stack (transport -> decoder resync -> assembler) must
        keep producing revolutions through ~20% frame damage."""
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import (
            SimConfig,
            SimulatedDevice,
        )

        from conftest import wait_for

        sim = SimulatedDevice(SimConfig(chaos=ChaosConfig(
            seed=2, corrupt_rate=0.15, truncate_rate=0.05,
        ))).start()
        drv = RealLidarDriver(
            channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
            motor_warmup_s=0.0,
        )
        try:
            assert drv.connect("", 0, True)
            drv.detect_and_init_strategy()
            assert drv.start_motor("DenseBoost", 600)
            got = []

            def grab():
                s = drv.grab_scan_data(timeout_s=0.5)
                if s is not None:
                    got.append(s)
                return len(got) >= 3
            assert wait_for(grab, 20.0), "no revolutions under chaos"
            assert sim.chaos_stream is not None
            faults = sim.chaos_stream.faults
            assert faults.get("corrupt", 0) + faults.get("truncate", 0) > 0
        finally:
            drv.disconnect()
            sim.stop()

    def test_mid_capsule_disconnect_severs_link(self):
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import (
            SimConfig,
            SimulatedDevice,
        )

        from conftest import wait_for

        sim = SimulatedDevice(SimConfig(chaos=ChaosConfig(
            disconnect_frames=(25,),
        ))).start()
        drv = RealLidarDriver(
            channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
            motor_warmup_s=0.0,
        )
        try:
            assert drv.connect("", 0, True)
            drv.detect_and_init_strategy()
            assert drv.start_motor("DenseBoost", 600)
            assert wait_for(lambda: not drv.is_connected(), 20.0), (
                "mid-capsule sever never surfaced as a dead link"
            )
            assert sim.chaos_stream.faults.get("disconnect") == 1
        finally:
            drv.disconnect()
            sim.stop()


# ---------------------------------------------------------------------------
# service-seam odds and ends
# ---------------------------------------------------------------------------


class TestServiceHealthSeam:
    def test_params_auto_attach_and_status(self):
        svc = ShardedFilterService(
            _params(fleet_ingest_backend="fused", health_enable=True),
            2, beams=BEAMS, fleet_ingest_buckets=(8,),
        )
        assert svc.health is not None
        st = svc.health_status()
        assert len(st) == 2 and all(s["state"] == "healthy" for s in st)

    def test_attach_order_hook_chaining_and_diagnostics(self):
        """attach_health BEFORE attach_mapper must still warm the
        mapper's quarantine row programs (a first quarantine never
        compiles in-loop); caller-installed transition hooks are
        chained after the service's checkpoint handlers, not dropped;
        and health_status() renders through the diagnostics updater's
        stream_health surface."""
        from rplidar_ros2_driver_tpu.node.diagnostics import (
            DiagnosticsUpdater,
        )
        from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState
        from rplidar_ros2_driver_tpu.node.publisher import (
            CollectingPublisher,
        )

        streams = 2
        ticks = _fleet_ticks(streams, 6)
        svc = ShardedFilterService(
            _map_params(fleet_ingest_backend="fused", map_backend="fused"),
            streams, beams=BEAMS, fleet_ingest_buckets=(8,),
        )
        svc._ensure_byte_ingest()
        svc.fleet_ingest.precompile([DENSE])
        fake = {"now": 0.0}
        fired = []
        health = FleetHealth(
            streams,
            HealthConfig(window_ticks=3, corrupt_ratio=0.5,
                         starvation_ticks=2, suspect_ticks=2,
                         probation_ticks=2, backoff_base_s=0.3,
                         backoff_jitter=0.0),
            clock=lambda: fake["now"],
            on_quarantine=lambda i: fired.append(("q", i)),
            on_recover=lambda i: fired.append(("r", i)),
        )
        svc.attach_health(health)   # health first...
        svc.attach_mapper()         # ...mapper second: must warm rows
        svc.mapper.precompile()
        assert svc.mapper._row_ops_cache is not None
        # stream 1 streams two revolutions, then goes silent ->
        # starvation quarantine -> release -> recovery on return
        cut = 4
        with guards.assert_no_recompile(tag="late-mapper quarantine"):
            for t, tick in enumerate(ticks):
                row = list(tick)
                if t >= cut and fired.count(("r", 1)) == 0:
                    row[1] = None  # silence until released
                svc.submit_bytes(row)
                fake["now"] += 0.2
        assert ("q", 1) in fired and ("r", 1) in fired  # chained hooks
        assert svc.quarantines >= 1 and svc.rejoins >= 1  # service hooks
        # the diagnostics surface fleet consumers feed health_status into
        upd = DiagnosticsUpdater("rig", CollectingPublisher())
        status = upd.update(
            lifecycle=LifecycleState.ACTIVE, fsm_state=None,
            port="fleet", rpm=0, device_info="",
            stream_health=svc.health_status(),
        )
        for i in range(streams):
            assert f"Stream {i} Health" in status.values
        with pytest.raises(ValueError):
            svc.attach_health(health, probes={0: lambda: 0})

    def test_backlog_drain_masks_quarantined_streams(self):
        ticks = _fleet_ticks(2, 4)
        svc = ShardedFilterService(
            _params(fleet_ingest_backend="fused"), 2,
            beams=BEAMS, fleet_ingest_buckets=(8,),
        )
        svc._ensure_byte_ingest()
        svc.fleet_ingest.precompile([DENSE])
        health = svc.attach_health(clock=lambda: 0.0)
        # force-quarantine stream 0 (unit seam: the FSM is tested above)
        health.health[0].state = StreamState.QUARANTINED
        health.health[0].release_at = 1e9
        results = svc.submit_bytes_backlog(ticks)
        assert results[0] == []          # masked throughout the drain
        assert len(results[1]) >= 2      # neighbor drained normally
