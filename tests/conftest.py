"""Test config: force the CPU backend with 8 virtual devices.

CI for this framework needs no TPU: all kernels are jit-compatible on the
CPU backend, and the multi-chip sharding tests run against a virtual
8-device host mesh (the driver's dryrun does the same).

NOTE: the env var JAX_PLATFORMS is NOT enough in this image — the axon
site shim overrides the jax *config* value to "axon,cpu" at interpreter
startup, which makes backend init dial the TPU tunnel first.  We must win
the override race with jax.config.update() before any backend initializes.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (sitecustomize has already imported it anyway)

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_collection_modifyitems(config, items):
    """soak_long tests run for minutes: skip them unless the operator
    selected the marker explicitly (``-m soak_long``)."""
    import pytest

    if "soak_long" in (config.option.markexpr or ""):
        return
    skip = pytest.mark.skip(reason="opt-in endurance soak: run with -m soak_long")
    for item in items:
        if "soak_long" in item.keywords:
            item.add_marker(skip)


def wait_for(predicate, timeout=20.0, interval=0.02):
    """Poll ``predicate`` until truthy or ``timeout`` elapses; returns
    whether it became true.  The one wait helper for all suites (was
    duplicated per test module)."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


class ScriptedTransceiver:
    """Queue-backed TransceiverLike fake shared by engine-level suites.

    ``q.put((ans_type, payload, is_loop))`` scripts answers; an empty
    queue behaves as a silent device (wait_message times out).  The
    optional ``channel`` exposes a raw-channel object for tests of the
    DTR/autobaud escape hatch.
    """

    def __init__(self, channel=None):
        import queue

        self.q = queue.Queue()
        self.sent = []
        self.channel = channel
        self.running = False

    def start(self):
        self.running = True
        return True

    def stop(self):
        self.running = False

    def send(self, packet):
        self.sent.append(bytes(packet))
        return True

    def wait_message(self, timeout_ms=1000):
        import queue

        try:
            return self.q.get(timeout=timeout_ms / 1000.0)
        except queue.Empty:
            return None

    def reset_decoder(self):
        pass

    @property
    def had_error(self):
        return False
