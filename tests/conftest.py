"""Test config: CPU backend with 8 virtual devices.

CI for this framework needs no TPU: all kernels are jit-compatible on the
CPU backend, and the multi-chip sharding tests run against a virtual
8-device host mesh (the driver's dryrun does the same).  Must run before
JAX initializes a backend, hence the env mutation at import time.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
