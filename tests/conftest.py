"""Test config: force the CPU backend with 8 virtual devices.

CI for this framework needs no TPU: all kernels are jit-compatible on the
CPU backend, and the multi-chip sharding tests run against a virtual
8-device host mesh (the driver's dryrun does the same).

NOTE: the env var JAX_PLATFORMS is NOT enough in this image — the axon
site shim overrides the jax *config* value to "axon,cpu" at interpreter
startup, which makes backend init dial the TPU tunnel first.  We must win
the override race with jax.config.update() before any backend initializes.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (sitecustomize has already imported it anyway)

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def wait_for(predicate, timeout=20.0, interval=0.02):
    """Poll ``predicate`` until truthy or ``timeout`` elapses; returns
    whether it became true.  The one wait helper for all suites (was
    duplicated per test module)."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False
