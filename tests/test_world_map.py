"""Shared-world mapping plane suite (mapping/worldmap + mapping/tiles
+ ops/tile_quant) — ROADMAP item 1's map-as-a-service layer.

The contracts under test:

  * QUANTIZATION — int8/int4 level coding round-trips within the
    published bound (band midpoint for occupied cells, EXACT zero for
    level 0 — unknown space never acquires phantom occupancy), nibble
    packing and run-length coding are lossless, long runs split at the
    16-bit wire cap.
  * FUSION GROUP — device fuse/retract match the numpy twin; merge
    order (in-arrival, shuffled, cross-shard partial sums) lands a
    byte-identical accumulation, and eviction subtracts a member's
    exact fused plane back out (``fuse_planes_np`` is the oracle).
  * ALIGNMENT — a whole-cell-translated copy of the reference aligns
    back byte-exactly (the corner-anchored pseudo-scan's sharp
    maximum), and the alignment doubles as the inter-stream pose-graph
    constraint.
  * SERVING — versioned immutable tile snapshots at the publish
    cadence, resident bytes bounded under eviction, compression over
    the dense grid, save/load byte-exact restore.
  * WIRING — the 6 new params validate, /diagnostics renders the
    "World Map" group (absent when off), and both services feed the
    world through the loop-engine tap or the cadence pull.
"""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.mapping.tiles import (
    TileConfig,
    publish_tiles,
    resolve_map_tile_backend,
    snapshot_grid,
)
from rplidar_ros2_driver_tpu.mapping.worldmap import (
    WORLD_STATE_VERSION,
    WorldConfig,
    WorldMap,
    shift_plane_np,
    world_config_from_params,
)
from rplidar_ros2_driver_tpu.ops.loop_close import derive_match_config
from rplidar_ros2_driver_tpu.ops.scan_match import SUB, MapConfig
from rplidar_ros2_driver_tpu.ops.tile_quant import (
    RUN_LEN_MAX,
    dequantize_plane,
    fuse_accumulate,
    fuse_planes_np,
    fuse_retract,
    min_tile_shift,
    pack_nibbles,
    quant_error_bound,
    quantize_plane,
    rle_decode,
    rle_encode,
    rle_payload_bytes,
    unpack_nibbles,
)

GRID = 64
Z3 = np.zeros((3,), np.int32)


def _map_cfg(**over) -> MapConfig:
    base = dict(grid=GRID, cell_m=0.1, beams=256)
    base.update(over)
    return MapConfig(**base)


def _world_cfg(backend: str = "int8", **over) -> WorldConfig:
    mc = over.pop("base", None) or _map_cfg()
    base = dict(
        base=mc,
        match=derive_match_config(mc, theta_window=4, window_cells=2),
        tile=TileConfig(
            grid=mc.grid, tile_cells=8, clamp_q=mc.clamp_q,
            backend=backend,
        ),
        max_submaps=4,
        merge_revs=2,
        publish_ticks=2,
    )
    base.update(over)
    return WorldConfig(**base)


def _blob_plane(seed: int, grid: int = GRID, n: int = 60) -> np.ndarray:
    """A sparse quantized submap plane: saturated occupied cells in
    the interior (the stored-plane value ceiling clamp_q >> quant_shift
    = 512 for the default geometry)."""
    rng = np.random.default_rng(seed)
    p = np.zeros((grid, grid), np.int32)
    idx = rng.integers(14, grid - 14, size=(n, 2))
    p[idx[:, 0], idx[:, 1]] = 512
    return p


# ---------------------------------------------------------------------------
# quantization + coding units (ops/tile_quant)
# ---------------------------------------------------------------------------


class TestTileQuant:
    def test_min_tile_shift(self):
        assert min_tile_shift(8192, 8) == 6    # 8192 >> 6 = 128 <= 255
        assert min_tile_shift(8192, 4) == 10   # 8192 >> 10 = 8 <= 15
        assert min_tile_shift(255, 8) == 0
        assert min_tile_shift(256, 8) == 1
        with pytest.raises(ValueError):
            min_tile_shift(0, 8)
        with pytest.raises(ValueError):
            min_tile_shift(8192, 0)

    @pytest.mark.parametrize("bits", [8, 4])
    def test_round_trip_error_bounds(self, bits):
        clamp = 8192
        shift = min_tile_shift(clamp, bits)
        bound = quant_error_bound(shift)
        assert bound == (1 << shift) >> 1
        rng = np.random.default_rng(7)
        plane = rng.integers(-clamp, clamp + 1, size=(64, 64)).astype(
            np.int32
        )
        lv = quantize_plane(plane, clamp, shift)
        assert lv.min() >= 0 and lv.max() <= (1 << bits) - 1
        deq = dequantize_plane(lv, shift)
        clipped = np.clip(plane, 0, clamp)
        occ = lv > 0
        # occupied cells land within the band-midpoint bound; level-0
        # cells reconstruct to EXACTLY 0 within the band width
        assert np.abs(deq[occ] - clipped[occ]).max() <= bound
        assert (deq[~occ] == 0).all()
        assert np.abs(deq[~occ] - clipped[~occ]).max() <= (1 << shift) - 1

    def test_level_zero_is_exactly_zero(self):
        deq = dequantize_plane(np.zeros((16,), np.int32), 6)
        assert (deq == 0).all()

    @pytest.mark.parametrize("n", [0, 1, 7, 8, 33])
    def test_nibble_pack_round_trip(self, n):
        rng = np.random.default_rng(n)
        lv = rng.integers(0, 16, size=(n,)).astype(np.int32)
        packed = pack_nibbles(lv)
        assert packed.dtype == np.uint8 and packed.size == (n + 1) // 2
        assert np.array_equal(unpack_nibbles(packed, n), lv)

    def test_rle_round_trip(self):
        rng = np.random.default_rng(11)
        lv = np.repeat(
            rng.integers(0, 256, size=(40,)),
            rng.integers(1, 30, size=(40,)),
        ).astype(np.int32)
        v, r = rle_encode(lv)
        assert v.size == r.size and (r >= 1).all()
        assert np.array_equal(rle_decode(v, r), lv)
        # empty stream round-trips empty
        v0, r0 = rle_encode(np.zeros((0,), np.int32))
        assert v0.size == 0 and rle_decode(v0, r0).size == 0

    def test_rle_long_run_splits_at_the_wire_cap(self):
        lv = np.full((RUN_LEN_MAX + 10,), 3, np.int32)
        v, r = rle_encode(lv)
        assert r.max() <= RUN_LEN_MAX
        assert v.size == 2 and (v == 3).all()
        assert int(r.sum()) == lv.size
        assert np.array_equal(rle_decode(v, r), lv)

    def test_rle_payload_accounting(self):
        # int8: 1 value byte + 2 run bytes per run; int4 packs nibbles
        assert rle_payload_bytes(10, 8) == 10 + 20
        assert rle_payload_bytes(10, 4) == 5 + 20
        assert rle_payload_bytes(11, 4) == 6 + 22
        assert rle_payload_bytes(0, 8) == 0

    def test_fuse_ops_match_the_numpy_twin(self):
        a = _blob_plane(1)
        b = _blob_plane(2)
        import jax

        acc = fuse_accumulate(jax.device_put(a.copy()), jax.device_put(b))
        assert np.array_equal(np.asarray(acc), a + b)
        back = fuse_retract(acc, jax.device_put(b))
        assert np.array_equal(np.asarray(back), a)
        # the shuffled-order oracle is the plain sum
        planes = [_blob_plane(s) for s in range(4)]
        ref = fuse_planes_np(planes)
        assert np.array_equal(
            fuse_planes_np([planes[2], planes[0], planes[3], planes[1]]),
            ref,
        )
        with pytest.raises(ValueError):
            fuse_planes_np([])


# ---------------------------------------------------------------------------
# tile plane (mapping/tiles)
# ---------------------------------------------------------------------------


class TestTilePlane:
    def test_resolve_backend(self):
        assert resolve_map_tile_backend("auto") == "int8"
        assert resolve_map_tile_backend("auto", platform="tpu") == "int8"
        for explicit in ("raw", "int8", "int4"):
            assert resolve_map_tile_backend(explicit) == explicit
        with pytest.raises(ValueError):
            resolve_map_tile_backend("int2")

    def test_tile_config_validation(self):
        with pytest.raises(ValueError):
            TileConfig(grid=64, tile_cells=12, clamp_q=8192)  # no divide
        with pytest.raises(ValueError):
            TileConfig(grid=64, tile_cells=0, clamp_q=8192)
        with pytest.raises(ValueError):
            TileConfig(grid=64, tile_cells=8, clamp_q=0)
        with pytest.raises(ValueError):
            TileConfig(grid=64, tile_cells=8, clamp_q=8192, backend="x")
        cfg = TileConfig(grid=64, tile_cells=8, clamp_q=8192,
                         backend="int4")
        assert cfg.bits == 4 and cfg.tiles_per_side == 8
        assert cfg.quant_shift == min_tile_shift(8192, 4)
        assert cfg.error_bound == quant_error_bound(cfg.quant_shift)
        raw = TileConfig(grid=64, tile_cells=8, clamp_q=8192,
                         backend="raw")
        assert raw.quant_shift == 0 and raw.error_bound == 0

    def test_raw_backend_round_trips_exactly(self):
        cfg = TileConfig(grid=GRID, tile_cells=8, clamp_q=8192,
                         backend="raw")
        plane = _blob_plane(3) * 7  # values past the stored ceiling
        snap = publish_tiles(plane, cfg, version=1)
        assert snap.version == 1 and snap.dense is not None
        # empty tiles dropped outright
        assert 0 < snap.tiles < cfg.tiles_per_side ** 2
        assert snap.payload_bytes == snap.dense.size * 4
        assert np.array_equal(
            snapshot_grid(snap), np.clip(plane, 0, cfg.clamp_q)
        )

    @pytest.mark.parametrize("backend", ["int8", "int4"])
    def test_quantized_round_trip_within_bound(self, backend):
        cfg = TileConfig(grid=GRID, tile_cells=8, clamp_q=8192,
                         backend=backend)
        rng = np.random.default_rng(5)
        plane = np.zeros((GRID, GRID), np.int32)
        idx = rng.integers(0, GRID, size=(300, 2))
        plane[idx[:, 0], idx[:, 1]] = rng.integers(1, 8193, size=300)
        snap = publish_tiles(plane, cfg, version=9)
        grid = snapshot_grid(snap)
        clipped = np.clip(plane, 0, cfg.clamp_q)
        occ = quantize_plane(plane, cfg.clamp_q, cfg.quant_shift) > 0
        assert np.abs(grid[occ] - clipped[occ]).max() <= cfg.error_bound
        assert (grid[~occ] == 0).all()

    def test_sparse_compression_beats_dense_int32(self):
        cfg = TileConfig(grid=GRID, tile_cells=8, clamp_q=8192,
                         backend="int8")
        snap = publish_tiles(_blob_plane(6), cfg, version=1)
        assert snap.raw_bytes == GRID * GRID * 4
        assert snap.compression_ratio > 3.0

    def test_int4_payload_at_most_int8(self):
        plane = _blob_plane(8)
        p8 = publish_tiles(
            plane,
            TileConfig(grid=GRID, tile_cells=8, clamp_q=8192,
                       backend="int8"),
            version=1,
        )
        p4 = publish_tiles(
            plane,
            TileConfig(grid=GRID, tile_cells=8, clamp_q=8192,
                       backend="int4"),
            version=1,
        )
        assert p4.payload_bytes <= p8.payload_bytes

    def test_empty_plane_publishes_zero_tiles(self):
        cfg = TileConfig(grid=GRID, tile_cells=8, clamp_q=8192,
                         backend="int8")
        snap = publish_tiles(np.zeros((GRID, GRID), np.int32), cfg, 1)
        assert snap.tiles == 0 and snap.payload_bytes == 0
        assert (snapshot_grid(snap) == 0).all()


# ---------------------------------------------------------------------------
# world merge: order independence, alignment, eviction
# ---------------------------------------------------------------------------


class TestWorldMerge:
    def test_merge_order_is_byte_irrelevant(self):
        """The tentpole contract: with the same frozen reference, ANY
        ingest order of the remaining submaps — in-arrival, shuffled,
        or interleaved across shards — lands a bit-identical
        accumulation, equal to the numpy oracle's plain sum of the
        aligned member planes."""
        ref = _blob_plane(99)
        planes = [_blob_plane(s) for s in range(5)]

        def run(order):
            w = WorldMap(_world_cfg(max_submaps=8))
            w.ingest_submap(0, ref, Z3)
            for k in order:
                w.ingest_submap(k + 1, planes[k], Z3)
            return w.save_state()

        s0 = run([0, 1, 2, 3, 4])
        for order in ([4, 2, 0, 3, 1], [3, 4, 1, 0, 2]):
            assert np.array_equal(run(order)["acc"], s0["acc"])
        member_planes = [m["plane"] for m in s0["members"]]
        assert np.array_equal(s0["acc"], fuse_planes_np(member_planes))
        # cross-shard partial sums: two half-fleet sums fused late are
        # the same bytes (associativity at the partial-sum granularity)
        half_a = fuse_planes_np(member_planes[:3])
        half_b = fuse_planes_np(member_planes[3:])
        assert np.array_equal(s0["acc"], half_a + half_b)

    def test_alignment_recovers_a_whole_cell_shift_exactly(self):
        """A translated copy of the reference aligns back byte-exactly
        (the corner-anchored pseudo-scan puts full bilinear weight on
        exactly one cell, so the true shift is a sharp maximum), the
        rotation stays zero, and the constraint row is the shift in
        subcells."""
        ref = _blob_plane(0)
        w = WorldMap(_world_cfg())
        w.ingest_submap(0, ref, Z3)
        for dx, dy in ((3, -2), (-5, 7)):
            shifted = shift_plane_np(ref, dx, dy)
            j = w.ingest_submap(1, shifted, Z3)
            m = w._members[j]
            assert m.weight == 1 and int(m.z[2]) == 0
            assert int(m.z[0]) % SUB == 0 and int(m.z[1]) % SUB == 0
            assert np.array_equal(m.plane, ref)
        # accumulation = reference + two aligned copies = 3x reference
        assert np.array_equal(w.save_state()["acc"], ref * 3)

    def test_empty_submap_fuses_at_zero_weight(self):
        w = WorldMap(_world_cfg())
        w.ingest_submap(0, _blob_plane(0), Z3)
        before = w.save_state()["acc"]
        j = w.ingest_submap(1, np.zeros((GRID, GRID), np.int32), Z3)
        m = w._members[j]
        assert m.weight == 0 and m.score == 0
        assert np.array_equal(w.save_state()["acc"], before)

    def test_eviction_is_exact_and_remaps_nodes(self):
        """Past the cap the oldest NON-reference member retracts: the
        accumulation returns byte-for-byte to the survivors' sum (the
        int32 group inverse) and node indices follow list positions —
        the pop IS the remap."""
        w = WorldMap(_world_cfg(max_submaps=3))
        w.ingest_submap(0, _blob_plane(0), Z3)
        w.ingest_submap(1, _blob_plane(1), Z3)
        w.ingest_submap(2, _blob_plane(2), Z3)
        assert len(w._members) == 3 and w.evictions == 0
        w.ingest_submap(3, _blob_plane(3), Z3)
        assert w.evictions == 1 and len(w._members) == 3
        state = w.save_state()
        assert [m["stream"] for m in state["members"]] == [0, 2, 3]
        assert np.array_equal(
            state["acc"],
            fuse_planes_np([m["plane"] for m in state["members"]]),
        )
        assert w.world_nodes().shape == (3, 3)

    def test_reference_never_evicts(self):
        w = WorldMap(_world_cfg())
        w.ingest_submap(0, _blob_plane(0), Z3)
        with pytest.raises(RuntimeError):
            w.evict_oldest()

    def test_align_without_reference_raises(self):
        w = WorldMap(_world_cfg())
        with pytest.raises(RuntimeError):
            w.align_submap(_blob_plane(0))

    def test_relaxed_nodes_hold_the_single_constraint(self):
        """One constraint against the gauge anchor relaxes to the
        measurement itself (zero residual at the seed) — the aligned
        shift IS the member's world pose."""
        ref = _blob_plane(0)
        w = WorldMap(_world_cfg())
        w.ingest_submap(0, ref, Z3)
        j = w.ingest_submap(1, shift_plane_np(ref, 4, -3), Z3)
        nodes = w.world_nodes()
        assert np.array_equal(nodes[0], Z3)
        assert np.array_equal(nodes[j], w._members[j].z)

    def test_merge_due_cadence(self):
        w = WorldMap(_world_cfg(merge_revs=4))
        assert not w.merge_due(0, 0)
        assert not w.merge_due(0, 3)
        assert w.merge_due(0, 4)
        w.note_merged(0, 4)
        assert not w.merge_due(0, 4)   # deduplicated per stream
        assert w.merge_due(1, 4)       # other streams independent
        assert w.merge_due(0, 8)


# ---------------------------------------------------------------------------
# serving: versioned snapshots, cadence, bounded residency, state carry
# ---------------------------------------------------------------------------


class TestWorldServing:
    def test_publish_cadence_and_versions(self):
        w = WorldMap(_world_cfg(publish_ticks=3))
        assert not w.tick()            # tick 1: nothing merged yet
        w.ingest_submap(0, _blob_plane(0), Z3)
        assert w.tick()                # tick 2: first snapshot is eager
        snap = w.publish()
        assert snap.version == 1 and w.snapshot() is snap
        assert not w.tick()            # tick 3: clean, nothing due
        w.ingest_submap(1, _blob_plane(1), Z3)
        assert not w.tick()            # tick 4: dirty, off the edge
        assert not w.tick()            # tick 5: still off the edge
        assert w.tick()                # tick 6: the cadence edge
        assert w.publish().version == 2

    def test_overlap_hook_is_the_due_publication(self):
        w = WorldMap(_world_cfg(publish_ticks=1))
        assert w.overlap_hook() is None
        w.ingest_submap(0, _blob_plane(0), Z3)
        hook = w.overlap_hook()
        assert callable(hook)
        hook()
        assert w.serving_version == 1 and w.snapshot() is not None
        assert w.overlap_hook() is None   # published: nothing due

    def test_snapshots_are_immutable_across_publishes(self):
        w = WorldMap(_world_cfg(publish_ticks=1))
        w.ingest_submap(0, _blob_plane(0), Z3)
        w.tick()
        snap1 = w.publish()
        grid1 = snapshot_grid(snap1).copy()
        values1 = snap1.values.copy()
        w.ingest_submap(1, _blob_plane(1), Z3)
        w.tick()
        snap2 = w.publish()
        assert snap2.version == 2 and w.snapshot() is snap2
        # the reader's held view never moved
        assert snap1.version == 1
        assert np.array_equal(snap1.values, values1)
        assert np.array_equal(snapshot_grid(snap1), grid1)

    def test_resident_bytes_bounded_under_eviction(self):
        cap = 3
        w = WorldMap(_world_cfg(max_submaps=cap, publish_ticks=1))
        g = GRID * GRID * 4
        bound = g * (cap + 1) + g   # acc + member planes + snapshot
        for k in range(10):
            w.ingest_submap(k, _blob_plane(k), Z3)
            if w.tick():
                w.publish()
            assert len(w._members) <= cap
            assert w.resident_bytes <= bound
        assert w.evictions == 10 - cap
        assert w.status()["evictions"] == w.evictions

    def test_status_payload_shape(self):
        w = WorldMap(_world_cfg(publish_ticks=1))
        st = w.status()
        assert st == {
            "backend": "int8", "nodes": 0, "tiles": 0,
            "resident_bytes": GRID * GRID * 4,
            "compression_ratio": 0.0, "merges": 0,
            "serving_version": 0, "evictions": 0,
        }
        w.ingest_submap(0, _blob_plane(0), Z3)
        w.tick()
        w.publish()
        st = w.status()
        assert st["nodes"] == 1 and st["merges"] == 1
        assert st["serving_version"] == 1 and st["tiles"] > 0
        assert st["compression_ratio"] > 3.0

    def test_save_load_round_trip_survives_eviction(self):
        w = WorldMap(_world_cfg(max_submaps=3))
        for k in range(4):
            w.ingest_submap(k, _blob_plane(k), Z3)
        state = w.save_state()
        w2 = WorldMap(_world_cfg(max_submaps=3))
        w2.load_state(state)
        s1, s2 = w.save_state(), w2.save_state()
        assert np.array_equal(s1["acc"], s2["acc"])
        assert len(s1["members"]) == len(s2["members"])
        for a, b in zip(s1["members"], s2["members"]):
            assert a["stream"] == b["stream"]
            assert np.array_equal(a["plane"], b["plane"])
            assert np.array_equal(a["z"], b["z"])
        assert s2["merges"] == s1["merges"]
        assert s2["evictions"] == s1["evictions"]
        # both sides keep evolving identically
        w.evict_oldest()
        w2.evict_oldest()
        assert np.array_equal(
            w.save_state()["acc"], w2.save_state()["acc"]
        )

    def test_load_rejects_version_and_geometry(self):
        w = WorldMap(_world_cfg())
        w.ingest_submap(0, _blob_plane(0), Z3)
        state = w.save_state()
        bad = dict(state)
        bad["version"] = WORLD_STATE_VERSION + 1
        with pytest.raises(ValueError):
            WorldMap(_world_cfg()).load_state(bad)
        small = _map_cfg(grid=32)
        w32 = WorldMap(_world_cfg(base=small))
        with pytest.raises(ValueError):
            w32.load_state(state)


# ---------------------------------------------------------------------------
# config + params
# ---------------------------------------------------------------------------


class TestWorldConfig:
    def test_world_config_validation(self):
        with pytest.raises(ValueError):
            _world_cfg(max_submaps=1)
        with pytest.raises(ValueError):
            _world_cfg(merge_revs=0)
        with pytest.raises(ValueError):
            _world_cfg(publish_ticks=0)
        mc = _map_cfg()
        with pytest.raises(ValueError):
            WorldConfig(
                base=mc,
                match=derive_match_config(
                    mc, theta_window=4, window_cells=2
                ),
                tile=TileConfig(grid=32, tile_cells=8, clamp_q=8192),
            )
        # the graph sizes with the membership cap
        cfg = _world_cfg(max_submaps=6)
        assert cfg.graph.max_nodes == 6
        assert cfg.graph.max_constraints == 5

    def test_world_config_from_params(self):
        from test_loop_close import _params
        from rplidar_ros2_driver_tpu.mapping.mapper import (
            map_config_from_params,
        )

        params = _params(
            world_map_enable=True, map_tile_backend="auto",
            world_tile_cells=8, world_max_submaps=4,
            world_merge_revs=3, world_publish_ticks=5,
        )
        mc = map_config_from_params(params, beams=256)
        cfg = world_config_from_params(params, mc)
        assert cfg.tile.backend == "int8"      # auto resolves
        assert cfg.tile.grid == mc.grid
        assert cfg.tile.tile_cells == 8
        assert cfg.max_submaps == 4
        assert cfg.merge_revs == 3 and cfg.publish_ticks == 5
        # the match derivation scores STORED quantized planes
        assert cfg.match.quant_shift == 0
        assert cfg.match.clamp_q == mc.clamp_q >> mc.quant_shift

    def test_param_validation(self):
        from test_loop_close import _params

        def validate(**kw):
            _params(**kw).validate()

        ok = _params(world_map_enable=True)
        ok.validate()
        assert ok.world_map_enable and ok.map_tile_backend == "auto"
        with pytest.raises(ValueError, match="map_tile_backend"):
            validate(map_tile_backend="int2")
        with pytest.raises(ValueError, match="world_map_enable"):
            validate(world_map_enable=True, map_enable=False,
                     loop_enable=False)
        with pytest.raises(ValueError, match="world_tile_cells"):
            validate(world_tile_cells=0)
        with pytest.raises(ValueError, match="world_tile_cells"):
            validate(world_tile_cells=7)   # must divide map_grid=64
        with pytest.raises(ValueError, match="world_max_submaps"):
            validate(world_max_submaps=1)
        with pytest.raises(ValueError, match="world_max_submaps"):
            validate(world_max_submaps=65)
        with pytest.raises(ValueError, match="world_merge_revs"):
            validate(world_merge_revs=0)
        with pytest.raises(ValueError, match="world_publish_ticks"):
            validate(world_publish_ticks=0)


# ---------------------------------------------------------------------------
# wiring: diagnostics + the service seams
# ---------------------------------------------------------------------------


def test_diagnostics_world_group_rendering():
    from rplidar_ros2_driver_tpu.node.diagnostics import DiagnosticsUpdater
    from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState

    class _Pub:
        def publish_diagnostics(self, status):
            self.last = status

    upd = DiagnosticsUpdater("rplidar-test", _Pub())
    status = upd.update(
        lifecycle=LifecycleState.ACTIVE, fsm_state=None,
        port="/dev/x", rpm=600, device_info="sim",
        world_map={
            "backend": "int8", "nodes": 3, "tiles": 12,
            "resident_bytes": 40960, "compression_ratio": 6.25,
            "merges": 7, "serving_version": 3, "evictions": 2,
        },
    )
    v = status.values
    assert v["World Map"] == "int8 v3"
    assert v["World Tiles"] == "12"
    assert v["World Resident Bytes"] == "40960"
    assert v["World Compression"] == "6.25x"
    assert v["World Merges"] == "7"
    assert v["World Evictions"] == "2"
    # absent group renders nothing
    status = upd.update(
        lifecycle=LifecycleState.ACTIVE, fsm_state=None,
        port="/dev/x", rpm=600, device_info="sim",
    )
    assert "World Map" not in status.values


def test_service_attach_world_map_via_loop_tap():
    """With a loop engine attached the world consumes the engine's OWN
    finalization product through on_install — one quantize path, no
    second pull."""
    from test_loop_close import _params, _scan
    from rplidar_ros2_driver_tpu.parallel.service import (
        ShardedFilterService,
    )
    from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh

    svc = ShardedFilterService(
        _params(filter_window=2, voxel_grid_size=32, loop_submap_revs=2,
                loop_check_revs=1, world_map_enable=True,
                world_merge_revs=2, world_tile_cells=8,
                world_max_submaps=4, world_publish_ticks=1),
        streams=2, mesh=make_mesh(2), beams=128,
    )
    svc.attach_loop_closure()
    world = svc.attach_world_map()
    assert svc.world is world
    for k in range(6):
        svc.submit([_scan(2 * k), _scan(2 * k + 1)])
    assert world.merges > 0            # finalizations fed the tap
    st = svc.world_status()
    assert st is not None and st["merges"] == world.merges
    # the drain epilogue's publication seam
    if world.tick():
        world.publish()
    assert world.serving_version >= 1 and world.snapshot() is not None


def test_service_world_cadence_pull_without_loop():
    """Without a loop engine the world pulls row snapshots at the
    world_merge_revs cadence, quantized through the ONE finalization
    path."""
    from test_loop_close import _params, _scan
    from rplidar_ros2_driver_tpu.parallel.service import (
        ShardedFilterService,
    )
    from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh

    svc = ShardedFilterService(
        _params(filter_window=2, voxel_grid_size=32, loop_enable=False,
                world_map_enable=True, world_merge_revs=2,
                world_tile_cells=8, world_max_submaps=4,
                world_publish_ticks=1),
        streams=2, mesh=make_mesh(2), beams=128,
    )
    world = svc.attach_world_map()    # attaches the mapper itself
    assert svc.mapper is not None and svc.loop is None
    for k in range(6):
        svc.submit([_scan(2 * k), _scan(2 * k + 1)])
    assert world.merges > 0
    # the cadence dedup held: at most one merge per (stream, revision)
    assert world.merges <= 2 * 3


def test_pod_world_map_cross_shard_merge_and_publish():
    """The pod seam: ONE world over every shard — merges arrive from
    both shards' lanes (the cross-shard fusion the order-independence
    contract makes safe) and a due tile publication lands during the
    pod drain without any extra dispatch path."""
    from test_chaos import _fleet_ticks, _map_params
    from test_fused_ingest import BEAMS
    from rplidar_ros2_driver_tpu.parallel.service import (
        ElasticFleetService,
    )
    from rplidar_ros2_driver_tpu.protocol.constants import Ans

    streams, shards = 4, 2
    params = _map_params(
        fleet_ingest_backend="fused", map_backend="fused",
        shard_count=shards, failover_snapshot_ticks=4,
        shard_starvation_ticks=500, sched_rungs=(1, 2),
        world_map_enable=True, world_merge_revs=2,
        world_tile_cells=8, world_max_submaps=4,
        world_publish_ticks=1,
    )
    pod = ElasticFleetService(
        params, streams, shards=shards, beams=BEAMS,
        fleet_ingest_buckets=(8,),
    )
    pod.attach_scheduler()
    pod.precompile([int(Ans.MEASUREMENT_DENSE_CAPSULED)])
    world = pod.attach_world_map()
    ticks = _fleet_ticks(streams, 10)
    for t in range(len(ticks)):
        pod.offer_bytes(list(ticks[t]))
        pod.drain_scheduled()
    assert world.merges > 0
    assert world.evictions == max(0, world.merges - 4)  # bounded set
    assert world.serving_version >= 1       # the drain published
    assert world.snapshot() is not None
    streams_seen = {m.stream for m in world._members}
    assert len(streams_seen) > 1            # genuinely cross-shard
    st = pod.world_status()
    assert st is not None and st["merges"] == world.merges
