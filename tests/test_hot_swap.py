"""Dynamic scan_mode hot-swap against the live protocol simulator.

The reference's most involved reconfigure path (parameters_callback
"scan_mode": stop motor -> 500 ms -> start_motor(new) -> fall back to
auto on failure, src/rplidar_node.cpp:740-770).  Everything else about
reconfigure is covered elsewhere; this exercises the swap end-to-end:
the device actually changes wire format mid-session and streaming
resumes, and an unknown mode lands on the driver's preference fallback
(DenseBoost) instead of killing the stream.
"""

import time

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
from rplidar_ros2_driver_tpu.node.fsm import FsmTimings
from rplidar_ros2_driver_tpu.node.node import RPlidarNode
from rplidar_ros2_driver_tpu.protocol.constants import Ans


def _wait_scans(node, n, timeout=20.0):
    base = node.publisher.scan_count
    t0 = time.monotonic()
    while node.publisher.scan_count < base + n:
        assert time.monotonic() - t0 < timeout, "stream stalled"
        time.sleep(0.05)


def _wait_ans_type(sim, ans, timeout=10.0):
    """Scan starts are fire-and-forget on the wire (send_only, like the
    reference), so the sim's rx thread observes the command a beat after
    start_motor returns — poll instead of racing it."""
    from conftest import wait_for

    assert wait_for(lambda: sim.active_ans_type == ans, timeout), (
        f"sim never switched to ans {ans} (at {sim.active_ans_type})"
    )


def test_scan_mode_hot_swap_and_fallback():
    sim = SimulatedDevice().start()
    node = None
    try:
        params = DriverParams(
            dummy_mode=False, channel_type="tcp", scan_mode="DenseBoost",
            filter_backend="cpu", filter_chain=(),
        )
        node = RPlidarNode(
            params,
            driver_factory=lambda: RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0),
            fsm_timings=FsmTimings(idle_tick_s=0.01),
        )
        assert node.configure()
        assert node.activate()
        _wait_scans(node, 2)
        assert node.fsm.driver.profile.active_mode == "DenseBoost"
        _wait_ans_type(sim, Ans.MEASUREMENT_DENSE_CAPSULED)

        # hot-swap to Standard: device switches wire format, stream resumes
        ok, msg = node.set_parameters({"scan_mode": "Standard"})
        assert ok, msg
        assert node.params.scan_mode == "Standard"
        _wait_scans(node, 2)
        assert node.fsm.driver.profile.active_mode == "Standard"
        _wait_ans_type(sim, Ans.MEASUREMENT)

        # a mode the device does not advertise: the DRIVER's preference
        # fallback kicks in (user pref -> DenseBoost -> Sensitivity,
        # src/lidar_driver_wrapper.cpp:207-245), so the swap still
        # succeeds and streaming resumes in the fallback mode
        ok, msg = node.set_parameters({"scan_mode": "NoSuchMode"})
        assert ok, msg
        _wait_scans(node, 2)
        assert node.fsm.driver.profile.active_mode == "DenseBoost"
        _wait_ans_type(sim, Ans.MEASUREMENT_DENSE_CAPSULED)
        assert node.fsm.reset_count == 0
    finally:
        if node is not None:
            node.shutdown()
        sim.stop()
