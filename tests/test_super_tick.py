"""T-tick super-step lowering vs T sequential fleet ticks — parity suite.

The super-step (ops/ingest.super_fleet_ingest_step) runs T fleet ticks
inside ONE compiled program: a ``lax.scan`` over the exact fleet-tick
body, every per-stream carry (decode state, partial revolution, filter
window, timestamp re-base) threaded as donated scan state.  This suite
pins the contract that makes the backlog drain shippable: **bit-exact**
outputs against the same ticks dispatched one per program, across

  * T in {1, 2, 8} (T=1 degenerates to the per-tick path: the engine
    must never regress when the lowering is disabled),
  * mixed answer types within one super-step (per-stream lax.switch),
  * corrupt/resync frames in the middle of a super-step,
  * carries surviving across super-step boundaries (a backlog longer
    than T splits into several super dispatches),
  * snapshot/restore mid-backlog,
  * the ShardedFilterService.submit_bytes_backlog drain seam (host
    backend as golden reference),
  * the structural dispatch claim: ceil(ticks/T) compiled dispatches,
    2 staged transfers each.

Bit-exactness here means the filter outputs and node-derived values are
identical.  Timestamps ride as f32 epoch offsets on both arms, but XLA
may contract their mul+add chains to FMA differently inside the scanned
program than in the standalone tick (1-ulp drift observed on CPU), so
ts0/duration compare to the host-parity suites' tolerance.
"""

from __future__ import annotations

import math

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest
from rplidar_ros2_driver_tpu.protocol.constants import Ans

from test_fused_ingest import BEAMS, TS_TOL, _params
from test_fleet_fused_ingest import _host_reference, _mk_ticks
from test_live_decode import _make_stream, _rng

DENSE = int(Ans.MEASUREMENT_DENSE_CAPSULED)


def _run_sequential(ticks, s, params=None, **kw):
    """The reference arm: the same engine, one dispatch per tick."""
    kw.setdefault("max_revs", 6)
    kw.setdefault("buckets", (4,))
    fleet = FleetFusedIngest(
        params or _params(), s, beams=BEAMS, super_tick_max=1, **kw
    )
    outs = [[] for _ in range(s)]
    for tick in ticks:
        for i, o in enumerate(fleet.submit(tick)):
            outs[i].extend(o)
    for i, o in enumerate(fleet.flush()):
        outs[i].extend(o)
    return outs, fleet


def _run_backlog(ticks, s, params=None, *, super_tick_max, **kw):
    kw.setdefault("max_revs", 6)
    kw.setdefault("buckets", (4,))
    fleet = FleetFusedIngest(
        params or _params(), s, beams=BEAMS,
        super_tick_max=super_tick_max, **kw
    )
    outs = fleet.submit_backlog(ticks)
    return outs, fleet


def _assert_identical(seq, sup):
    """Fused-vs-fused: node values and filter outputs must be EXACTLY
    equal.  Timestamps are f32 arithmetic whose mul+add XLA may contract
    to FMA differently in the scanned program than in the standalone
    tick (1-ulp drift observed on CPU), so stamps compare to the same
    tolerance the host-parity suites use."""
    assert len(seq) == len(sup)
    for i, (a_outs, b_outs) in enumerate(zip(seq, sup)):
        assert len(a_outs) == len(b_outs), (
            f"stream {i}: sequential {len(a_outs)} revs vs super {len(b_outs)}"
        )
        for k, ((oa, ta, da), (ob, tb, db)) in enumerate(zip(a_outs, b_outs)):
            for field in (
                "ranges", "intensities", "points_xy", "point_mask", "voxel"
            ):
                assert np.array_equal(
                    np.asarray(getattr(oa, field)),
                    np.asarray(getattr(ob, field)),
                ), f"stream {i} rev {k}: {field}"
            assert abs(ta - tb) < TS_TOL and abs(da - db) < TS_TOL, (
                i, k, ta, tb, da, db,
            )


class TestSuperTickParity:
    @pytest.mark.parametrize("super_t", [1, 2, 8])
    def test_t_values_bit_exact(self, super_t):
        """The acceptance matrix: T in {1, 2, 8} super-steps vs the same
        ticks dispatched sequentially, plus the ceil(ticks/T) dispatch
        count and the 2-transfers-per-dispatch staging claim."""
        sf = [
            (DENSE, _make_stream(
                Ans.MEASUREMENT_DENSE_CAPSULED, 40, _rng(),
                syncs=(0, 10 + i, 25),
            ))
            for i in range(2)
        ]
        ticks = _mk_ticks(sf, np.random.default_rng(super_t))
        seq, _ = _run_sequential(ticks, 2)
        sup, fleet = _run_backlog(ticks, 2, super_tick_max=super_t)
        _assert_identical(seq, sup)
        assert sum(len(s) for s in sup) >= 2, "fixture closed no revs"
        # every tick is one slice at this bucket size, so the structural
        # claim is exact: ceil(ticks/T) dispatches, 2 transfers each
        assert fleet.dispatch_count == math.ceil(len(ticks) / super_t)
        assert fleet.h2d_transfers == 2 * fleet.dispatch_count
        if super_t > 1:
            assert fleet.ticks_super_fused >= 2
        else:
            assert fleet.super_dispatches == 0

    def test_host_golden_reference(self):
        """The super drain is also bit-exact against N independent HOST
        decode+assembly+chain paths (the transitive anchor: per-tick
        fused is pinned to host by test_fleet_fused_ingest; this pins
        super -> host directly so a drift in either hop surfaces)."""
        sf = [
            (DENSE, _make_stream(
                Ans.MEASUREMENT_DENSE_CAPSULED, 40, _rng(), syncs=(0, 10, 25)
            ))
            for _ in range(3)
        ]
        ticks = _mk_ticks(sf, np.random.default_rng(31))
        host = _host_reference(ticks, 3)
        sup, _ = _run_backlog(ticks, 3, super_tick_max=4)
        for i in range(3):
            assert len(host[i]) == len(sup[i])
            for (ho, hts0, hdur), (fo, fts0, fdur) in zip(host[i], sup[i]):
                for field in ("ranges", "voxel"):
                    assert np.array_equal(
                        np.asarray(getattr(ho, field)),
                        np.asarray(getattr(fo, field)),
                    ), (i, field)
                assert abs(hts0 - fts0) < TS_TOL
                assert abs(hdur - fdur) < TS_TOL

    def test_mixed_ans_types_in_super_step(self):
        """Three formats live inside ONE super-step: per-stream
        lax.switch dispatch under the scan."""
        sf = [
            (int(a), _make_stream(a, 36, _rng(), syncs=(0, 9, 18, 27)))
            for a in (
                Ans.MEASUREMENT_DENSE_CAPSULED,
                Ans.MEASUREMENT_HQ,
                Ans.MEASUREMENT,
            )
        ]
        ticks = _mk_ticks(sf, np.random.default_rng(11))
        seq, _ = _run_sequential(ticks, 3)
        sup, _ = _run_backlog(ticks, 3, super_tick_max=4)
        _assert_identical(seq, sup)

    def test_all_six_formats_one_fleet(self):
        """Every measurement wire format rides one six-stream fleet
        through the super drain — the acceptance matrix's format axis,
        paired prev-frame carries and smoothing carries included."""
        from test_fused_ingest import ALL_FORMATS

        assert len(ALL_FORMATS) == 6
        sf = [
            (int(a), _make_stream(a, 60, _rng(), syncs=(0, 15, 30, 45)))
            for a in ALL_FORMATS
        ]
        ticks = _mk_ticks(sf, np.random.default_rng(29))
        seq, _ = _run_sequential(ticks, 6)
        sup, _ = _run_backlog(ticks, 6, super_tick_max=4)
        _assert_identical(seq, sup)
        assert all(len(s) >= 1 for s in sup), [len(s) for s in sup]

    def test_corrupt_resync_inside_super_step(self):
        """Checksum faults (and the resync they force) land mid-backlog
        on one stream: fault isolation must survive the scan carries."""
        a = Ans.MEASUREMENT_DENSE_CAPSULED
        healthy = _make_stream(a, 40, _rng(), syncs=(0, 10, 25))
        corrupt = _make_stream(
            a, 40, _rng(), syncs=(0,), corrupt=(7, 8, 19, 30)
        )
        sf = [(DENSE, healthy), (DENSE, corrupt), (DENSE, healthy)]
        ticks = _mk_ticks(sf, np.random.default_rng(9))
        seq, _ = _run_sequential(ticks, 3)
        sup, _ = _run_backlog(ticks, 3, super_tick_max=8)
        _assert_identical(seq, sup)

    def test_carries_across_super_step_boundaries(self):
        """A backlog longer than T splits into several super dispatches:
        every carry (partial revolution, prev frame, sync edge,
        timestamp re-base) must survive the boundary between two scanned
        programs exactly as it survives a per-tick boundary."""
        sf = [
            (DENSE, _make_stream(
                Ans.MEASUREMENT_DENSE_CAPSULED, 48, _rng(), syncs=(0,)
            ))
            for _ in range(2)
        ]
        ticks = _mk_ticks(sf, np.random.default_rng(17))
        assert len(ticks) > 3  # several T=3 groups + a ragged tail
        seq, _ = _run_sequential(ticks, 2)
        sup, fleet = _run_backlog(ticks, 2, super_tick_max=3)
        _assert_identical(seq, sup)
        assert fleet.dispatch_count == math.ceil(len(ticks) / 3)

    def test_format_switch_mid_backlog(self):
        """One stream switches scan modes in the middle of the backlog:
        the decode-state reset must land at ITS tick inside the scan
        (the baked-in per-slice snapshots), not at the drain head."""
        a1, a2 = Ans.MEASUREMENT_DENSE_CAPSULED, Ans.MEASUREMENT_HQ
        s0_first = _make_stream(a1, 24, _rng(), syncs=(0, 8, 16))
        s0_second = _make_stream(a2, 20, _rng(), syncs=(0, 5, 10, 15))
        s1 = _make_stream(a1, 44, _rng(), syncs=(0, 11, 22, 33))
        rng = np.random.default_rng(13)
        t1 = _mk_ticks([(int(a1), s0_first), (DENSE, s1[:22])], rng)
        t2 = _mk_ticks([(int(a2), s0_second), (DENSE, s1[22:])], rng)
        ticks = t1 + t2
        seq, _ = _run_sequential(ticks, 2)
        sup, _ = _run_backlog(ticks, 2, super_tick_max=4)
        _assert_identical(seq, sup)
        assert sum(len(s) for s in sup) >= 4


    def test_format_switch_mid_backlog_with_prior_traffic(self):
        """The case that actually bites: the engine already has
        per-stream timestamp bases from LIVE traffic when a backlog
        containing a format switch arrives.  Normalizing every backlog
        tick up front must not clear a base that an earlier tick's
        staging still needs — the reset (and its fresh base) must land
        at its own tick inside the drain, or every pre-switch
        revolution's ts0 shifts by the stall gap."""
        a1, a2 = Ans.MEASUREMENT_DENSE_CAPSULED, Ans.MEASUREMENT_HQ
        s0_first = _make_stream(a1, 24, _rng(), syncs=(0, 8, 16))
        s0_second = _make_stream(a2, 20, _rng(), syncs=(0, 5, 10, 15))
        s1 = _make_stream(a1, 44, _rng(), syncs=(0, 11, 22, 33))
        rng = np.random.default_rng(37)
        ticks = (
            _mk_ticks([(int(a1), s0_first), (DENSE, s1[:22])], rng)
            + _mk_ticks([(int(a2), s0_second), (DENSE, s1[22:])], rng)
        )
        live = 3  # ticks submitted live before the stall
        params = _params()

        def run(backlog: bool):
            eng = FleetFusedIngest(
                params, 2, beams=BEAMS, max_revs=6, buckets=(4,),
                super_tick_max=4,
            )
            outs = [[] for _ in range(2)]
            for tick in ticks[:live]:  # live traffic establishes bases
                for i, o in enumerate(eng.submit(tick)):
                    outs[i].extend(o)
            if backlog:
                for i, o in enumerate(eng.submit_backlog(ticks[live:])):
                    outs[i].extend(o)
            else:
                for tick in ticks[live:]:
                    for i, o in enumerate(eng.submit(tick)):
                        outs[i].extend(o)
                for i, o in enumerate(eng.flush()):
                    outs[i].extend(o)
            return outs

        _assert_identical(run(backlog=False), run(backlog=True))


class TestSnapshotRestoreMidBacklog:
    def test_snapshot_restore_between_super_steps(self):
        """Drain half the backlog, snapshot, restore into a FRESH
        engine, drain the rest: identical outputs to the uninterrupted
        super drain — the scanned carries round-trip through the
        checkpoint surface."""
        sf = [
            (DENSE, _make_stream(
                Ans.MEASUREMENT_DENSE_CAPSULED, 40, _rng(), syncs=(0,)
            ))
            for _ in range(2)
        ]
        ticks = _mk_ticks(sf, np.random.default_rng(19))
        cut = len(ticks) // 2
        params = _params()

        ref, _ = _run_backlog(ticks, 2, params, super_tick_max=3)

        a = FleetFusedIngest(
            params, 2, beams=BEAMS, max_revs=6, buckets=(4,),
            super_tick_max=3,
        )
        outs = [list(o) for o in a.submit_backlog(ticks[:cut])]
        snap = a.snapshot()
        b = FleetFusedIngest(
            params, 2, beams=BEAMS, max_revs=6, buckets=(4,),
            super_tick_max=3,
        )
        assert b.restore(snap)
        for i, o in enumerate(b.submit_backlog(ticks[cut:])):
            outs[i].extend(o)
        _assert_identical(ref, outs)
        assert sum(len(o) for o in outs) >= 1


class TestEngineSemantics:
    def test_oversized_tick_splits_into_super_step(self):
        """A single tick whose frame run exceeds the largest bucket
        splits into slices — with the lowering enabled those slices
        drain as ONE super dispatch instead of one each."""
        frames = _make_stream(
            Ans.MEASUREMENT_DENSE_CAPSULED, 36, _rng(), syncs=(0, 9, 18)
        )
        t = 50.0
        batch = []
        for f in frames:
            t += 0.002
            batch.append((f, t))
        tick = [(DENSE, batch)]

        seq_eng = FleetFusedIngest(
            _params(), 1, beams=BEAMS, max_revs=6, buckets=(4,),
            super_tick_max=1,
        )
        seq = seq_eng.submit(tick)
        seq_disp = seq_eng.dispatch_count
        assert seq_disp == 9  # 36 frames / bucket 4

        sup_eng = FleetFusedIngest(
            _params(), 1, beams=BEAMS, max_revs=6, buckets=(4,),
            super_tick_max=16,
        )
        sup = sup_eng.submit(tick)
        assert sup_eng.dispatch_count == 1
        assert sup_eng.super_dispatches == 1
        _assert_identical(seq, sup)

    def test_super_tick_param_flows_from_driver_params(self):
        p = _params(super_tick_max=5)
        eng = FleetFusedIngest(p, 1, beams=BEAMS, buckets=(4,))
        assert eng.super_tick_max == 5
        eng = FleetFusedIngest(p, 1, beams=BEAMS, buckets=(4,),
                               super_tick_max=2)
        assert eng.super_tick_max == 2  # explicit kwarg wins
        with pytest.raises(ValueError):
            FleetFusedIngest(p, 1, beams=BEAMS, super_tick_max=0)
        with pytest.raises(ValueError):
            DriverParams(super_tick_max=0).validate()

    def test_staging_buffers_are_recycled(self):
        """The per-bucket staging planes must recycle through the free
        list instead of allocating fresh each tick (the alloc-churn
        satellite) — and a pair is only recycled AFTER its dispatch's
        results were fetched, so reuse can never alias an in-flight
        dispatch's input even under zero-copy host-buffer semantics."""
        sf = [(DENSE, _make_stream(
            Ans.MEASUREMENT_DENSE_CAPSULED, 24, _rng(), syncs=(0, 8)
        ))]
        ticks = _mk_ticks(sf, np.random.default_rng(7), idle_prob=0.0)
        eng = FleetFusedIngest(
            _params(), 1, beams=BEAMS, max_revs=6, buckets=(4,),
            super_tick_max=1,
        )
        # the blocking submit fetches its own tick's results, so each
        # tick's pair lands back on the free list before the next tick
        eng.submit(ticks[0])
        free = eng._staging_free[("tick", 4)]
        assert len(free) == 1
        buf0, aux0 = free[0]
        for tick in ticks[1:]:
            eng.submit(tick)
        free = eng._staging_free[("tick", 4)]
        assert len(free) == 1  # steady state: one pair, recycled forever
        assert free[0][0] is buf0 and free[0][1] is aux0
        assert eng.dispatch_count >= len(ticks)
        # while a dispatch is UNFETCHED its pair must stay off the free
        # list (submit_pipelined defers the fetch by one tick)
        eng2 = FleetFusedIngest(
            _params(), 1, beams=BEAMS, max_revs=6, buckets=(4,),
            super_tick_max=1,
        )
        eng2.submit_pipelined(ticks[0])
        assert len(eng2._staging_free.get(("tick", 4), [])) == 0
        eng2.flush()
        assert len(eng2._staging_free[("tick", 4)]) == 1


class TestServiceBacklogSeam:
    def test_submit_bytes_backlog_both_backends(self):
        """The service's catch-up seam: the fused backend drains the
        backlog through the super-step (all completions returned, in
        tick order, bit-exact vs the per-tick fused engine); the host
        backend replays the same ticks through the lockstep golden
        path and publishes through the same seam."""
        from rplidar_ros2_driver_tpu.parallel.service import (
            ShardedFilterService,
        )

        frames = _make_stream(
            Ans.MEASUREMENT_DENSE_CAPSULED, 40, _rng(), syncs=(0, 10, 25)
        )
        sf = [(DENSE, frames), (DENSE, frames)]
        ticks = _mk_ticks(sf, np.random.default_rng(23), idle_prob=0.0)

        svc = ShardedFilterService(
            _params(fleet_ingest_backend="fused", super_tick_max=4), 2,
            beams=BEAMS, fleet_ingest_buckets=(4,),
        )
        got = svc.submit_bytes_backlog(ticks)
        assert svc.fleet_ingest is not None
        assert svc.fleet_ingest.super_dispatches >= 1
        assert svc.fleet_ingest.dispatch_count < len(
            [t for t in ticks if any(t)]
        )

        ref, _ = _run_sequential(ticks, 2)
        for i in range(2):
            assert len(got[i]) == len(ref[i]) >= 1
            for out, (ho, _, _) in zip(got[i], ref[i]):
                assert np.array_equal(
                    np.asarray(out.ranges), np.asarray(ho.ranges)
                )

        svc_h = ShardedFilterService(
            _params(fleet_ingest_backend="host"), 2, beams=BEAMS
        )
        svc_h.precompile()
        got_h = svc_h.submit_bytes_backlog(ticks)
        assert all(len(s) >= 1 for s in got_h)

    def test_backlog_validates_stream_count(self):
        from rplidar_ros2_driver_tpu.parallel.service import (
            ShardedFilterService,
        )

        svc = ShardedFilterService(
            _params(fleet_ingest_backend="host"), 2, beams=BEAMS
        )
        with pytest.raises(ValueError):
            svc.submit_bytes_backlog([[None]])  # 1 run for a 2-stream fleet


class TestStagingPool:
    def test_take_give_round_trip_recycles_zeroed(self):
        from rplidar_ros2_driver_tpu.driver.ingest import StagingPool

        pool = StagingPool()
        key = ("tick", 4)
        buf, aux = pool.take(key, (2, 4, 84), (2, 12))
        assert buf.shape == (2, 4, 84) and aux.shape == (2, 12)
        assert pool.pooled() == 0
        buf[:] = 7
        aux[:] = 3.5
        pool.give(key, (buf, aux))
        assert pool.pooled() == 1
        buf2, aux2 = pool.take(key, (2, 4, 84), (2, 12))
        # recycled, not reallocated — and scrubbed back to zero
        assert buf2 is buf and aux2 is aux
        assert not buf2.any() and not aux2.any()
        assert pool.pooled() == 0

    def test_stale_shapes_are_dropped_not_served(self):
        from rplidar_ros2_driver_tpu.driver.ingest import StagingPool

        pool = StagingPool()
        key = ("tick", 2)
        pool.give(key, pool.take(key, (1, 4, 84), (1, 12)))
        # the payload width moved: the pooled pair cannot serve this
        # request and must not survive under the key either
        buf, aux = pool.take(key, (1, 4, 132), (1, 12))
        assert buf.shape == (1, 4, 132)
        assert pool.pooled() == 0

    def test_keys_are_independent(self):
        from rplidar_ros2_driver_tpu.driver.ingest import StagingPool

        pool = StagingPool()
        a = pool.take(("tick", 1), (1, 4, 84), (1, 12))
        pool.give(("tick", 1), a)
        b, _aux = pool.take(("tick", 2), (1, 4, 84), (1, 12))
        assert b is not a[0]
        assert pool.pooled() == 1  # ("tick", 1)'s pair is untouched

    def test_engine_staging_free_is_the_pool_view(self):
        eng = FleetFusedIngest(
            _params(), 1, beams=BEAMS, max_revs=6, buckets=(4,),
        )
        assert eng._staging_free is eng.staging._free

    def test_elastic_pod_shares_one_pool_per_host(self):
        from rplidar_ros2_driver_tpu.parallel.service import (
            ElasticFleetService,
        )

        pod = ElasticFleetService(
            _params(fleet_ingest_backend="fused"), 4, shards=2,
            hosts=2, beams=BEAMS, fleet_ingest_buckets=(4,),
        )
        assert len(pod.staging_pools) == 2
        for s, sh in enumerate(pod.shards):
            sh._ensure_byte_ingest()
            host = pod.topology.host_of(s)
            assert sh.fleet_ingest.staging is pod.staging_pools[host]
        # single-host pod: every shard shares the ONE pool
        pod1 = ElasticFleetService(
            _params(fleet_ingest_backend="fused"), 4, shards=2,
            beams=BEAMS, fleet_ingest_buckets=(4,),
        )
        assert len(pod1.staging_pools) == 1
        for sh in pod1.shards:
            sh._ensure_byte_ingest()
        assert (
            pod1.shards[0].fleet_ingest.staging
            is pod1.shards[1].fleet_ingest.staging
        )
