"""RawNodeHolder semantics + the driver's interval-grab path over the sim.

Behavioral contract from the reference's RawSampleNodeHolder
(sl_lidar_driver.cpp:186-235) and getScanDataWithIntervalHq (:962-966).
"""

import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu import native as native_mod
from rplidar_ros2_driver_tpu.driver.assembly import RawNodeHolder


def chunk(start, k):
    a = np.arange(start, start + k, dtype=np.int32)
    return np.stack([a, a * 2, a % 64, np.zeros_like(a)], axis=1)


class TestRawNodeHolder:
    def test_fetch_returns_in_arrival_order_and_drains(self):
        h = RawNodeHolder(capacity=100)
        h.push(chunk(0, 10))
        h.push(chunk(10, 5))
        out = h.fetch()
        assert out.shape == (15, 4)
        np.testing.assert_array_equal(out[:, 0], np.arange(15))
        assert h.fetch() is None

    def test_capacity_drops_oldest(self):
        h = RawNodeHolder(capacity=8)
        h.push(chunk(0, 6))
        h.push(chunk(6, 6))   # 12 > 8: oldest 4 dropped
        out = h.fetch()
        assert out.shape == (8, 4)
        np.testing.assert_array_equal(out[:, 0], np.arange(4, 12))
        assert h.nodes_dropped == 4

    def test_max_nodes_partial_fetch_keeps_rest(self):
        h = RawNodeHolder(capacity=100)
        h.push(chunk(0, 10))
        first = h.fetch(max_nodes=4)
        np.testing.assert_array_equal(first[:, 0], np.arange(4))
        rest = h.fetch()
        np.testing.assert_array_equal(rest[:, 0], np.arange(4, 10))

    def test_reset_clears(self):
        h = RawNodeHolder()
        h.push(chunk(0, 3))
        h.reset()
        assert h.fetch() is None


@pytest.mark.skipif(not native_mod.available(), reason="native library unavailable")
def test_interval_grab_over_sim():
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice

    sim = SimulatedDevice().start()
    try:
        drv = RealLidarDriver(
            channel_type="tcp",
            tcp_host=SimulatedDevice.TARGET,
            tcp_port=sim.port,
            motor_warmup_s=0.0,
        )
        assert drv.connect("ignored", 0, True)
        drv.detect_and_init_strategy()
        assert drv.start_motor("DenseBoost", 600)
        deadline = time.monotonic() + 10.0
        total = 0
        while total < 500 and time.monotonic() < deadline:
            nodes = drv.grab_scan_data_with_interval()
            if nodes is None:
                time.sleep(0.01)
                continue
            assert nodes.ndim == 2 and nodes.shape[1] == 4
            # angles are Q14 within a turn
            assert (nodes[:, 0] >= 0).all() and (nodes[:, 0] < 65536).all()
            total += len(nodes)
        assert total >= 500, f"only {total} raw nodes arrived"
        drv.disconnect()
    finally:
        sim.stop()
