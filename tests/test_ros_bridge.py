"""rclpy bridge gating: importable everywhere, constructible only with ROS.

Full topic behavior can only run on a ROS 2 host (rclpy is not in this
CI image); what must hold here is that the module imports cleanly
without rclpy, reports availability honestly, and fails construction
with ImportError (the documented contract) rather than something
surprising.
"""

import pytest

from rplidar_ros2_driver_tpu.tools import ros_bridge


def test_importable_and_reports_availability():
    assert isinstance(ros_bridge.rclpy_available(), bool)


def test_constructor_requires_rclpy():
    if ros_bridge.rclpy_available():  # pragma: no cover - ROS host
        pytest.skip("rclpy present: constructor would succeed")
    with pytest.raises(ImportError):
        ros_bridge.RclpyPublisher()


def test_invalid_qos_rejected_before_any_ros_import():
    """The QoS vocabulary check precedes the rclpy imports, so a typo'd
    reliability fails loudly (ValueError) even without ROS installed."""
    with pytest.raises(ValueError, match="qos_reliability"):
        ros_bridge.RclpyPublisher(qos_reliability="RELIABLE")


def test_is_a_publisher_base():
    from rplidar_ros2_driver_tpu.node.publisher import PublisherBase

    assert issubclass(ros_bridge.RclpyPublisher, PublisherBase)
