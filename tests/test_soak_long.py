"""Opt-in minutes-scale endurance soak (r3 VERDICT #7).

The automated analog of the reference's community stress protocol
(README.md:27-38: long runs with hot-plug and RPM changes, watch for
degradation): the full node stack streams DenseBoost wire frames over a
REAL pty serial plane at 3x any real S2's pace while the harness
periodically yanks the "cable" (closing the pty master — EIO on the
slave, exactly what a pulled USB adapter produces) and changes RPM
mid-stream.  Each replug appears at a fresh pty path, modelling USB
re-enumeration; the FSM's driver factory picks it up.

Skipped by default (it runs for minutes); select it explicitly:

    SOAK_LONG_SECONDS=180 python -m pytest tests/test_soak_long.py -m soak_long -q

Writes a JSON artifact (default ``artifacts/soak_long.json``) recording
scan throughput, per-generation assembler drops, decode counts,
unplug-to-recovery latencies, and revolution-size spread (the sync-
health signal: resync damage shows up as wild revolution sizes).
"""

import json
import os
import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import (
    SerialSimulatedDevice,
    SimConfig,
)
from rplidar_ros2_driver_tpu.node.fsm import FsmTimings
from rplidar_ros2_driver_tpu.node.node import RPlidarNode, launch
from rplidar_ros2_driver_tpu.node.publisher import CollectingPublisher

from conftest import wait_for

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _CountingPublisher(CollectingPublisher):
    """Bounded collector plus O(1) per-scan stats (a minutes-long run at
    30 rev/s must not hold every LaserScan in memory)."""

    def __init__(self):
        super().__init__(maxlen=8)
        self.beam_counts: list[int] = []

    def publish_scan(self, msg) -> None:
        super().publish_scan(msg)
        self.beam_counts.append(int(np.isfinite(msg.ranges).sum()))


@pytest.mark.soak_long
def test_endurance_serial_soak_with_replug_cycles():
    seconds = float(os.environ.get("SOAK_LONG_SECONDS", 150.0))
    cycle_s = float(os.environ.get("SOAK_LONG_CYCLE_S", 25.0))
    artifact_path = os.environ.get(
        "SOAK_LONG_ARTIFACT", os.path.join(_REPO, "artifacts", "soak_long.json")
    )
    # 3x DenseBoost: 3200 pts/rev @ 10 rev/s = 800 frames/s nominal
    cfg = SimConfig(points_per_rev=3200, frame_rate_hz=2400.0)

    sims: list[SerialSimulatedDevice] = []
    params = DriverParams(
        channel_type="serial", scan_mode="DenseBoost",
        filter_backend="cpu", filter_chain=(), max_retries=3,
    )

    def factory() -> RealLidarDriver:
        # replug at a fresh pty: an unplugged pty cannot reappear at the
        # same path (kernel names /dev/pts), which conveniently models a
        # USB adapter re-enumerating — the FSM reconnects via
        # params.serial_port, so point it at the new device
        for old in sims[:-1]:
            old.stop()  # reap earlier generations
        sim = SerialSimulatedDevice(cfg).start()
        sims.append(sim)
        params.serial_port = sim.port_path
        return RealLidarDriver(channel_type="serial", motor_warmup_s=0.0)

    pub = _CountingPublisher()
    node = RPlidarNode(params, pub, driver_factory=factory,
                       fsm_timings=FsmTimings.fast())

    generations: list[dict] = []

    def sample_generation() -> None:
        drv = node.fsm.driver if node.fsm else None
        if drv is None or getattr(drv, "_assembler", None) is None:
            return
        generations.append({
            "scans_completed": int(drv._assembler.scans_completed),
            "scans_dropped": int(drv._assembler.scans_dropped),
            "nodes_decoded": int(drv._scan_decoder.nodes_decoded),
            "points_emitted": int(sims[-1].points_emitted),
        })

    recoveries: list[float] = []
    rpm_schedule = (400, 800, 600)
    rpm_applied = 0
    t_start = time.monotonic()
    launch(node)
    try:
        assert wait_for(lambda: pub.scan_count >= 1, 60.0), "never streamed"
        t_end = t_start + seconds
        cycle = 0
        while time.monotonic() < t_end:
            # first half-cycle: steady streaming, then an RPM change
            # mid-stream (the community protocol's second stressor)
            half = min(cycle_s / 2, max(t_end - time.monotonic(), 0))
            time.sleep(half)
            ok, _ = node.set_parameters({"rpm": rpm_schedule[cycle % 3]})
            rpm_applied += bool(ok)
            # second half-cycle, then yank the cable — only if enough
            # budget remains for the recovery to be observed fairly
            time.sleep(min(cycle_s / 2, max(t_end - time.monotonic(), 0)))
            if time.monotonic() + 15.0 < t_end:
                sample_generation()
                resets_before = node.fsm.reset_count
                t_unplug = time.monotonic()
                sims[-1].unplug()
                # recovery = unplug -> FSM reset observed -> first scan of
                # the NEW stream.  Gating on the reset first keeps a
                # revolution already in flight at the yank from reading
                # as a milliseconds "recovery".
                assert wait_for(
                    lambda: node.fsm.reset_count > resets_before, 60.0
                ), f"no reset after unplug (cycle {cycle})"
                base = pub.scan_count
                assert wait_for(lambda: pub.scan_count > base, 60.0), (
                    f"no recovery after unplug (cycle {cycle})"
                )
                recoveries.append(time.monotonic() - t_unplug)
            cycle += 1
        sample_generation()
        total_resets = node.fsm.reset_count
    finally:
        node.shutdown()
        for sim in sims:
            sim.stop()

    wall = time.monotonic() - t_start
    counts = np.asarray(pub.beam_counts[1:] or [0])  # first rev may be partial
    completed = sum(g["scans_completed"] for g in generations)
    dropped = sum(g["scans_dropped"] for g in generations)
    artifact = {
        "seconds_requested": seconds,
        "seconds_wall": round(wall, 1),
        "pace": "3x DenseBoost (2400 frames/s, 3200 pts/rev)",
        "transport": "serial (pty, fresh path per replug)",
        "scans_published": pub.scan_count,
        "scans_per_sec": round(pub.scan_count / wall, 2),
        "unplug_cycles": len(recoveries),
        "recovery_latencies_s": [round(r, 3) for r in recoveries],
        "recovery_p50_s": round(float(np.median(recoveries)), 3) if recoveries else None,
        "recovery_max_s": round(max(recoveries), 3) if recoveries else None,
        "rpm_changes_applied": rpm_applied,
        "resets": total_resets,
        "generations": generations,
        "assembler_completed_total": completed,
        "assembler_dropped_total": dropped,
        "beam_count_median": int(np.median(counts)),
        "beam_count_p5": int(np.percentile(counts, 5)),
        "beam_count_p95": int(np.percentile(counts, 95)),
        "date": time.strftime("%Y-%m-%d"),
    }
    os.makedirs(os.path.dirname(artifact_path), exist_ok=True)
    with open(artifact_path, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    print(json.dumps(artifact))

    # endurance criteria: the stream survived every yank, recovered
    # promptly each time, kept the newest-wins drops bounded, and
    # revolution sizes stayed sane (sync damage shows up here)
    assert len(recoveries) >= 2, "soak too short to exercise replug cycles"
    assert max(recoveries) < 30.0, recoveries
    assert dropped <= 0.2 * completed + 2 * max(len(generations), 1), (
        dropped, completed,
    )
    assert pub.scan_count >= 5.0 * wall * 0.3, (pub.scan_count, wall)
    lo, hi = int(np.percentile(counts, 5)), int(np.percentile(counts, 95))
    assert 2000 <= lo and hi <= 4000, (lo, hi)


@pytest.mark.slow
def test_chaos_fleet_soak_quarantine_cycles_stay_bit_exact():
    """Minutes-scale chaos soak at fleet scale (the slow extension of
    the tier-1 chaos smoke in tests/test_chaos.py): a fleet of 4 runs
    hundreds of ticks while TWO streams take repeated seeded fault
    bursts — corruption, truncation, stall windows — cycling through
    quarantine/recovery several times each.  Criteria: every faulty
    stream quarantined AND recovered at least twice, healthy streams
    never left HEALTHY, zero recompiles/implicit transfers across the
    whole steady-state span, and every published output plus the final
    per-stream maps are bit-exact against the host-golden replay of
    the identical masked byte stream."""
    from rplidar_ros2_driver_tpu.driver.chaos import ChaosConfig, chaos_ticks
    from rplidar_ros2_driver_tpu.driver.health import (
        FleetHealth,
        HealthConfig,
        StreamState,
    )
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
    from rplidar_ros2_driver_tpu.utils import guards

    from test_chaos import (
        DENSE,
        OUT_FIELDS,
        _fleet_ticks,
        _host_replay,
        _map_params,
    )

    streams = 4
    # floor of 60 revolutions: the repeated-cycle assertions below need
    # enough stream for several stall windows per faulty stream
    revs = max(60, int(os.environ.get("CHAOS_SOAK_REVS", 60)))
    ticks = _fleet_ticks(streams, revs)
    n_frames = revs * 10
    # streams 1 and 2: repeating fault cycles — periodic stall windows
    # (starvation-driven quarantines) over a floor of corruption and
    # truncation, phase-shifted so the quarantines overlap sometimes
    # and not others; the last ~10 revolutions run clean so both
    # streams finish the soak recovered
    stop = max(n_frames - 100, 1)
    cfgs = {
        1: ChaosConfig(seed=31, start_frame=30, stop_frame=stop,
                       stall_period=120, stall_frames=30,
                       corrupt_rate=0.1, truncate_rate=0.05),
        2: ChaosConfig(seed=32, start_frame=80, stop_frame=stop,
                       stall_period=150, stall_frames=35,
                       corrupt_rate=0.15),
    }
    cticks = chaos_ticks(ticks, cfgs)

    params = _map_params(fleet_ingest_backend="fused", map_backend="fused")
    from test_fused_ingest import BEAMS

    svc = ShardedFilterService(
        params, streams, beams=BEAMS, fleet_ingest_buckets=(8,)
    )
    svc._ensure_byte_ingest()
    svc.fleet_ingest.precompile([DENSE])
    svc.attach_mapper()
    svc.mapper.precompile()
    fake = {"now": 0.0}
    health = FleetHealth(
        streams,
        HealthConfig(window_ticks=3, corrupt_ratio=0.5, starvation_ticks=3,
                     suspect_ticks=2, probation_ticks=2,
                     backoff_base_s=0.3, backoff_max_s=1.2,
                     backoff_jitter=0.0, seed=7),
        clock=lambda: fake["now"],
        probes={1: lambda: 0, 2: lambda: 0},
        record_masks=True,
    )
    svc.attach_health(health)

    outs_log = []
    warm = 3
    t0 = time.monotonic()
    for tick in cticks[:warm]:
        outs_log.append(list(svc.submit_bytes(tick)))
        fake["now"] += 0.1
    with guards.steady_state(tag="chaos soak"):
        for tick in cticks[warm:]:
            outs_log.append(list(svc.submit_bytes(tick)))
            fake["now"] += 0.1
    wall = time.monotonic() - t0

    # repeated full cycles on BOTH faulty streams; healthy ones
    # untouched; everyone recovered by the clean tail
    for s in (1, 2):
        assert health.health[s].quarantines >= 2, health.status()[s]
        assert health.health[s].recoveries >= 2, health.status()[s]
        assert health.health[s].state is StreamState.HEALTHY, (
            health.status()[s]
        )
    for s in (0, 3):
        assert health.health[s].quarantines == 0
        assert health.health[s].state is StreamState.HEALTHY
    assert svc.rejoins >= 4 and not svc.stream_checkpoints

    # host-golden replay of the identical masked stream, bit-exact
    rejoins = {
        s: {t for (t, s2, _o, new) in health.events
            if s2 == s and new == "recovering"}
        for s in range(streams)
    }
    per_tick, host_mappers = _host_replay(
        cticks, health.mask_log, rejoins, streams,
        _map_params(map_backend="host"),
    )
    published = 0
    for t, row in enumerate(outs_log):
        for i in range(streams):
            h, f = per_tick[t][i], row[i]
            assert (h is None) == (f is None), (t, i)
            if h is None:
                continue
            published += 1
            for field in OUT_FIELDS:
                assert np.array_equal(
                    np.asarray(getattr(h, field)),
                    np.asarray(getattr(f, field)),
                ), (t, i, field)
    assert published >= revs  # the soak actually streamed at scale
    for i in range(streams):
        fused_row = svc.mapper.snapshot_stream(i)
        host_row = host_mappers[i].snapshot_stream(0)
        for k in ("log_odds", "pose", "origin_xy", "revision"):
            assert np.array_equal(fused_row[k], host_row[k]), (i, k)
    print(
        f"chaos soak: {len(cticks)} ticks / {published} published in "
        f"{wall:.1f}s; quarantines="
        f"{[h.quarantines for h in health.health]}"
    )
