"""De-skew + sweep reconstruction (ops/deskew.py) — parity suite.

Pins the contract that makes the stage shippable inside the fused
ingest core (ops/ingest._segment_filter_core):

  * every fixed-point building block is BIT-EXACT between the jnp
    lowering and the NumPy twin (ops/deskew_ref.py) — int32 end to end,
    so equality is byte-level, not tolerance;
  * zero motion is the exact identity (a stationary platform's outputs
    are untouched, estimator and applicator both);
  * the motion estimator recovers synthetic rotations/translations with
    the documented sign conventions;
  * the full streaming surface — reconstructed sweep planes, motion
    estimates, de-skewed revolution outputs — is bit-exact between the
    host twin and ALL fused lowerings: single-stream, fleet 1/3/8,
    super-tick T∈{1,2,8};
  * the cache respects the engine seams: ring invalidation on a
    mid-backlog format switch, decode-carry reset on a quarantine-style
    rejoin (the ring restarts with the engines, like PR 9's
    ``_streaming`` flag), bit-exact continuation through whole-fleet
    and per-stream snapshot/restore.
"""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.ops import wire
from rplidar_ros2_driver_tpu.ops.deskew import (
    RECON_EMPTY,
    DeskewConfig,
    apply_deskew,
    combine_ring,
    deskew_config_from_params,
    estimate_motion,
    profile_from_nodes,
    profile_trig,
    push_ring,
    rasterize_subsweep,
)
from rplidar_ros2_driver_tpu.ops.deskew_ref import (
    DeskewHostTwin,
    HostDeskewStream,
    apply_deskew_np,
    combine_ring_np,
    estimate_motion_np,
    profile_from_nodes_np,
    rasterize_subsweep_np,
    wire_clamp_np,
)
from rplidar_ros2_driver_tpu.protocol.constants import Ans

BEAMS = 256
ANS = int(Ans.MEASUREMENT_DENSE_CAPSULED)

DSK = DeskewConfig(
    recon_beams=BEAMS, profile_beams=64, shift_window=4, recon_window=3
)


def _params(**over):
    base = dict(
        filter_backend="cpu",
        filter_chain=("clip", "median", "voxel"),
        filter_window=4,
        voxel_grid_size=32,
        ingest_backend="fused",
        deskew_enable=True,
        sweep_reconstruct_window=3,
        deskew_profile_beams=64,
        deskew_shift_window=4,
    )
    base.update(over)
    return DriverParams(**base)


def _dense_frames(revs: int, ppr: int = 400, drift_per_rev: float = 0.0,
                  seed: int = 0):
    """Dense-capsule wire stream: ``revs`` revolutions of a sinusoidal
    room, with an optional radial drift per revolution (a "moving
    platform" whose motion the estimator must pick up)."""
    rng = np.random.default_rng(seed)
    frames = []
    idx = 0
    first = True
    while idx < revs * ppr:
        theta = 360.0 * (idx % ppr) / ppr
        pts = (np.arange(40) + idx) % ppr
        dists = (
            2000.0 + 500.0 * np.sin(2 * np.pi * pts / ppr)
            + drift_per_rev * (idx / ppr)
            + rng.uniform(0.0, 0.25)
        )
        frames.append(wire.encode_dense_capsule(
            int(theta * 64) & 0x7FFF, first, dists.astype(int)
        ))
        idx += 40
        first = False
    return frames


def _chunks(frames, run=4):
    return [frames[i : i + run] for i in range(0, len(frames), run)]


def _feed_single(cfg_deskew, frames, run=4, max_nodes=1024, max_revs=2):
    """Drive the raw single-stream fused step over ``frames``; returns
    the per-dispatch IngestBatchResult list."""
    from rplidar_ros2_driver_tpu.ops.filters import FilterConfig
    from rplidar_ros2_driver_tpu.ops.ingest import (
        create_ingest_state,
        fused_ingest_step,
        ingest_config_for,
        unpack_ingest_result,
    )
    from rplidar_ros2_driver_tpu.protocol import timing as timingmod

    fcfg = FilterConfig(window=4, beams=BEAMS, grid=32)
    cfg = ingest_config_for(
        ANS, timingmod.TimingDesc(), fcfg,
        max_nodes=max_nodes, max_revs=max_revs, deskew=cfg_deskew,
    )
    st = create_ingest_state(cfg)
    outs = []
    t = 100.0
    prev_base = None
    for ch in _chunks(frames, run):
        m = len(ch)
        stamps = []
        for _ in ch:
            t += 0.00125
            stamps.append(t)
        base = stamps[0]
        buf = np.zeros((run, cfg.frame_bytes), np.uint8)
        buf[:m] = np.frombuffer(b"".join(ch), np.uint8).reshape(m, -1)
        aux = np.zeros((2 * run + 2,), np.float32)
        aux[:m] = [s - base for s in stamps]
        aux[-2] = 0.0 if prev_base is None else prev_base - base
        aux[-1] = m
        prev_base = base
        st, *res = fused_ingest_step(st, buf, aux, cfg=cfg)
        outs.append(unpack_ingest_result(res, cfg))
    return outs, st, cfg


# ---------------------------------------------------------------------------
# fixed-point building blocks: jnp vs numpy, byte-for-byte
# ---------------------------------------------------------------------------


def _rand_nodes(rng, n=600):
    angle = rng.integers(0, 65536, n).astype(np.int32)
    dist = rng.integers(0, 0x3FFFF, n).astype(np.int32)
    dist[rng.random(n) < 0.1] = 0  # no-return markers
    quality = rng.integers(0, 256, n).astype(np.int32)
    valid = rng.random(n) < 0.9
    return angle, dist, quality, valid


def test_block_parity_random():
    rng = np.random.default_rng(3)
    for _ in range(5):
        angle, dist, quality, valid = _rand_nodes(rng)
        pj = np.asarray(profile_from_nodes(angle, dist, valid, DSK))
        pn = profile_from_nodes_np(angle, dist, valid, DSK)
        assert np.array_equal(pj, pn)

        a2, d2, _q, v2 = _rand_nodes(rng)
        p2 = profile_from_nodes_np(a2, d2, v2, DSK)
        mj = np.asarray(estimate_motion(pn, p2, DSK))
        mn = estimate_motion_np(pn, p2, DSK)
        assert np.array_equal(mj, mn)

        aj, dj = apply_deskew(angle, dist, valid, mn, DSK)
        an, dn = apply_deskew_np(angle, dist, valid, mn, DSK)
        assert np.array_equal(np.asarray(aj), an)
        assert np.array_equal(np.asarray(dj), dn)

        sj = np.asarray(rasterize_subsweep(angle, dist, quality, valid, DSK))
        sn = rasterize_subsweep_np(angle, dist, quality, valid, DSK)
        assert np.array_equal(sj, sn)


def test_ring_combine_parity_and_newest_wins():
    rng = np.random.default_rng(5)
    import jax.numpy as jnp

    ring = np.full((DSK.recon_window, BEAMS), RECON_EMPTY, np.int32)
    pos = 0
    jring = jnp.asarray(ring)
    jpos = jnp.asarray(0, jnp.int32)
    for k in range(7):
        angle, dist, quality, valid = _rand_nodes(rng, 200)
        seg = rasterize_subsweep_np(angle, dist, quality, valid, DSK)
        ring[pos % DSK.recon_window] = seg
        pos += 1
        jring, jpos = push_ring(
            jring, jpos, jnp.asarray(seg), jnp.asarray(True)
        )
        cj = np.asarray(combine_ring(jring, jpos))
        cn = combine_ring_np(ring, pos)
        assert np.array_equal(cj, cn)
        # newest-wins: every beam the NEWEST segment touched shows its
        # value, regardless of what older segments held there
        touched = seg != RECON_EMPTY
        assert np.array_equal(cn[touched], seg[touched])
    # an un-pushed tick leaves ring and position untouched
    jring2, jpos2 = push_ring(
        jring, jpos, jnp.asarray(seg), jnp.asarray(False)
    )
    assert np.array_equal(np.asarray(jring2), np.asarray(jring))
    assert int(jpos2) == int(jpos)


# ---------------------------------------------------------------------------
# estimator semantics: identity, rotation, translation
# ---------------------------------------------------------------------------


def _room_profile(cfg) -> np.ndarray:
    d = cfg.profile_beams
    return (
        4000 + 1500 * np.sin(2 * np.pi * np.arange(d) / d * 3.0)
    ).astype(np.int32)


def test_zero_motion_identity_units():
    prof = _room_profile(DSK)
    m = estimate_motion_np(prof, prof.copy(), DSK)
    assert np.array_equal(m, np.zeros(3, np.int32))
    rng = np.random.default_rng(11)
    angle, dist, _q, valid = _rand_nodes(rng)
    a2, d2 = apply_deskew_np(angle, dist, valid, np.zeros(3, np.int32), DSK)
    assert np.array_equal(a2, angle) and np.array_equal(d2, dist)
    # featureless tie (all shifts score equally): |s|-ordered candidates
    # make first-min-wins prefer the identity
    flat = np.full((DSK.profile_beams,), 5000, np.int32)
    assert np.array_equal(
        estimate_motion_np(flat, flat.copy(), DSK), np.zeros(3, np.int32)
    )


def test_estimator_recovers_rotation():
    prof = _room_profile(DSK)
    d = DSK.profile_beams
    for s0 in (-3, -1, 1, 3):
        # sensor rotated by dθ = s0 beams: a feature at beam b in the
        # previous revolution appears at beam b - s0 now, i.e.
        # cur[b] = prev[b + s0]
        cur = np.roll(prof, -s0)
        m = estimate_motion_np(prof, cur, DSK)
        assert m[2] == s0 * (65536 // d), (s0, m)


def test_estimator_recovers_translation():
    prof = _room_profile(DSK)
    trig = profile_trig(DSK)
    for dx, dy in ((300, 0), (0, -250), (200, 150)):
        radial = (dx * trig[:, 0] + dy * trig[:, 1] + (1 << 13)) >> 14
        cur = (prof - radial).astype(np.int32)
        m = estimate_motion_np(prof, cur, DSK)
        assert m[2] == 0
        # diagonal least squares on a 3-lobed room: expect the right
        # sign and magnitude within ~25%
        for est, true in ((m[0], dx), (m[1], dy)):
            if true == 0:
                assert abs(int(est)) <= 64
            else:
                assert np.sign(est) == np.sign(true)
                assert abs(int(est) - true) <= abs(true) * 0.25 + 32


def test_apply_deskew_phase_fraction():
    motion = np.asarray([0, 0, 512], np.int32)  # dθ = 2 profile beams
    angle = np.asarray([0, 32768, 65535], np.int32)  # phase 0, ½, ~1
    dist = np.full(3, 8000, np.int32)
    a2, _d2 = apply_deskew_np(angle, dist, np.ones(3, bool), motion, DSK)
    # full remaining motion at phase 0, half at phase ½, ~none at the end
    assert a2[0] == (0 - 512) % 65536
    assert a2[1] == (32768 - 256) % 65536
    assert a2[2] == 65535
    # pure translation: range shrinks by the remaining radial component
    motion = np.asarray([400, 0, 0], np.int32)
    _a2, d2 = apply_deskew_np(angle, dist, np.ones(3, bool), motion, DSK)
    assert d2[0] == 8000 - 400      # cos(0)=1, full phase remaining
    assert d2[1] == 8000 + 200      # cos(π)=-1, half remaining
    assert d2[2] == 8000            # no motion left
    # a no-return node is never resurrected
    _a3, d3 = apply_deskew_np(
        np.zeros(1, np.int32), np.zeros(1, np.int32), np.ones(1, bool),
        motion, DSK,
    )
    assert d3[0] == 0


def test_config_validation():
    with pytest.raises(ValueError):
        DeskewConfig(recon_beams=BEAMS, profile_beams=48)  # not 2^k
    with pytest.raises(ValueError):
        DeskewConfig(recon_beams=BEAMS, shift_window=0)
    with pytest.raises(ValueError):
        DeskewConfig(recon_beams=BEAMS, recon_window=1)
    with pytest.raises(ValueError):
        DeskewConfig(recon_beams=BEAMS, max_trans_q2=1 << 14)
    with pytest.raises(ValueError):
        _params(filter_chain=()).validate()
    with pytest.raises(ValueError):
        _params(ingest_backend="host", fleet_ingest_backend="host").validate()
    with pytest.raises(ValueError):
        _params(sweep_reconstruct_window=1).validate()
    with pytest.raises(ValueError):
        _params(deskew_profile_beams=100).validate()
    with pytest.raises(ValueError):
        _params(deskew_shift_window=99).validate()
    p = _params()
    p.validate()
    dsk = deskew_config_from_params(p, BEAMS)
    assert dsk is not None and dsk.recon_beams == BEAMS
    assert deskew_config_from_params(
        DriverParams(), BEAMS
    ) is None


# ---------------------------------------------------------------------------
# streaming surface: host twin vs every fused lowering
# ---------------------------------------------------------------------------


def test_single_stream_vs_host_twin_moving_scene():
    """The whole streaming surface — recon planes, motion estimates,
    per-revolution de-skewed chain outputs — bit-exact between the
    single-stream fused engine and the NumPy twin + golden chain, on a
    scene with real inter-revolution motion (nonzero estimates)."""
    from rplidar_ros2_driver_tpu.driver.ingest import FusedIngest
    from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain

    params = _params()
    frames = _dense_frames(6, drift_per_rev=60.0)
    eng = FusedIngest(params, beams=BEAMS, capacity=1024, max_revs=2,
                      buckets=(4,))
    eng.recon_log = True
    twin = DeskewHostTwin(deskew_config_from_params(params, BEAMS),
                          max_nodes=1024)
    chain = ScanFilterChain(params, beams=BEAMS, warmup=False)

    t = 100.0
    twin_recons, twin_ranges = [], []
    for ch in _chunks(frames, 4):
        items = []
        for f in ch:
            t += 0.00125
            items.append((f, t))
        eng.on_measurement_batch(ANS, list(items))
        combined, pushed, revs = twin.tick(ANS, items)
        if pushed:
            twin_recons.append(combined)
        for a2, d2, scan in revs:
            out = chain.process_raw(a2, d2, scan["quality"], scan["flag"])
            twin_ranges.append(np.asarray(out.ranges).copy())
    fused_outs = eng.flush()

    assert len(eng.recon_history) == len(twin_recons) > 0
    for k, ((plane, pts), tw) in enumerate(
        zip(eng.recon_history, twin_recons)
    ):
        assert np.array_equal(plane, tw), f"recon plane {k} diverged"
        assert pts.shape == (BEAMS, 3)
    assert len(fused_outs) == len(twin_ranges) > 0
    moved = False
    for k, ((out, _ts0, _dur), tr) in enumerate(
        zip(fused_outs, twin_ranges)
    ):
        assert np.array_equal(np.asarray(out.ranges), tr), (
            f"revolution {k} de-skewed output diverged"
        )
    # the drifting scene must actually exercise the estimator
    assert (twin.stream.motion != 0).any()


@pytest.mark.parametrize("streams", [1, 3, 8])
def test_fleet_vs_single_stream(streams):
    """Fleet lanes are bit-exact vs the single-stream fused path: same
    per-tick recon planes, motion meta and revolution outputs for every
    lane fed the same bytes."""
    from rplidar_ros2_driver_tpu.ops.filters import FilterConfig
    from rplidar_ros2_driver_tpu.ops.ingest import (
        create_fleet_ingest_state,
        fleet_aux_len,
        fleet_fused_ingest_step,
        fleet_ingest_config_for,
        unpack_fleet_ingest_result,
    )
    from rplidar_ros2_driver_tpu.protocol import timing as timingmod

    frames = _dense_frames(4, drift_per_rev=60.0)
    run = 4
    single, _st, _cfg = _feed_single(DSK, frames, run=run)

    fcfg = FilterConfig(window=4, beams=BEAMS, grid=32)
    cfg = fleet_ingest_config_for(
        (ANS,), timingmod.TimingDesc(), fcfg,
        max_nodes=1024, max_revs=2, deskew=DSK,
    )
    st = create_fleet_ingest_state(cfg, streams)
    t0s = [100.0 + 50.0 * i for i in range(streams)]
    prevb = [None] * streams
    for ci, ch in enumerate(_chunks(frames, run)):
        m = len(ch)
        buf = np.zeros((streams, run, cfg.frame_bytes), np.uint8)
        aux = np.zeros((streams, fleet_aux_len(run)), np.float32)
        for i in range(streams):
            stamps = [t0s[i] + 0.00125 * (ci * run + j + 1) for j in range(m)]
            base = stamps[0]
            buf[i, :m] = np.frombuffer(b"".join(ch), np.uint8).reshape(m, -1)
            aux[i, :m] = [s - base for s in stamps]
            aux[i, 2 * run] = 0.0 if prevb[i] is None else prevb[i] - base
            aux[i, 2 * run + 1] = m
            prevb[i] = base
        st, *res = fleet_fused_ingest_step(st, buf, aux, cfg=cfg)
        rows = unpack_fleet_ingest_result(res, cfg)
        ref = single[ci]
        for i in range(streams):
            assert rows[i].recon_pushed == ref.recon_pushed
            assert np.array_equal(rows[i].recon_plane, ref.recon_plane)
            assert np.array_equal(rows[i].recon_pts, ref.recon_pts)
            assert np.array_equal(rows[i].deskew_motion, ref.deskew_motion)
            assert rows[i].n_completed == ref.n_completed
            for k in range(ref.n_completed):
                assert np.array_equal(
                    rows[i].outputs[k].ranges, ref.outputs[k].ranges
                )


@pytest.mark.parametrize("super_t", [1, 2, 8])
def test_super_tick_vs_per_tick(super_t):
    """The T-tick super-step carries the de-skew/reconstruction planes
    through its lax.scan bit-exactly: same recon planes and outputs as
    T sequential per-tick dispatches."""
    from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest

    frames = _dense_frames(4, drift_per_rev=60.0)
    run = 4

    def drive(stm):
        eng = FleetFusedIngest(
            _params(fleet_ingest_backend="fused"), 2, beams=BEAMS,
            capacity=1024, max_revs=2, buckets=(run,), super_tick_max=stm,
        )
        eng.recon_log = True
        ticks = []
        t = [100.0, 150.0]
        for ch in _chunks(frames, run):
            tick = []
            for s in range(2):
                batch = []
                for f in ch:
                    t[s] += 0.00125
                    batch.append((f, t[s]))
                tick.append((ANS, batch))
            ticks.append(tick)
        outs = eng.submit_backlog(ticks)
        return eng, outs

    eng1, outs1 = drive(1)
    engT, outsT = drive(super_t)
    for i in range(2):
        assert len(eng1.recon_history[i]) == len(engT.recon_history[i]) > 0
        for (p1, x1), (pt, xt) in zip(
            eng1.recon_history[i], engT.recon_history[i]
        ):
            assert np.array_equal(p1, pt)
            assert np.array_equal(x1, xt)
        assert len(outs1[i]) == len(outsT[i]) > 0
        for (o1, _t1, _d1), (oT, _tT, _dT) in zip(outs1[i], outsT[i]):
            assert np.array_equal(
                np.asarray(o1.ranges), np.asarray(oT.ranges)
            )
    if super_t > 1:
        assert engT.super_dispatches > 0


# ---------------------------------------------------------------------------
# cache seams: format switch, snapshot/restore, rejoin reset
# ---------------------------------------------------------------------------


def test_ring_invalidation_on_format_switch():
    """A mid-backlog format switch resets the sub-sweep ring with the
    decode carries: the first post-switch reconstruction contains ONLY
    post-switch data (bit-exact vs a FRESH twin fed only the post-
    switch ticks)."""
    from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest

    params = _params(fleet_ingest_backend="fused")
    dense = _dense_frames(2)
    run = 4
    eng = FleetFusedIngest(params, 1, beams=BEAMS, capacity=1024,
                           max_revs=2, buckets=(run,))
    eng.recon_log = True
    # normal-measurement frames after the switch (1 node per frame)
    normal = []
    ppr = 64
    for k in range(ppr * 2):
        a_deg = 360.0 * (k % ppr) / ppr
        normal.append(wire.encode_normal_node(
            int(a_deg * 64) & 0x7FFF, (3000 + 10 * (k % ppr)) * 4,
            40, k % ppr == 0,
        ))
    ticks = []
    t = [100.0]

    def mk(ans, ch):
        batch = []
        for f in ch:
            t[0] += 0.00125
            batch.append((f, t[0]))
        return [(ans, batch)]

    for ch in _chunks(dense, run):
        ticks.append(mk(ANS, ch))
    switch_at = len(ticks)
    for ch in _chunks(normal, run):
        ticks.append(mk(int(Ans.MEASUREMENT), ch))
    eng.submit_backlog(ticks)

    # the twin sees only the post-switch stream from a fresh state
    twin = DeskewHostTwin(
        deskew_config_from_params(params, BEAMS), max_nodes=1024
    )
    twin_recons = []
    for tk in ticks[switch_at:]:
        combined, pushed, _revs = twin.tick(tk[0][0], tk[0][1])
        if pushed:
            twin_recons.append(combined)
    post = eng.recon_history[0][-len(twin_recons):]
    assert len(twin_recons) > 0
    for (plane, _pts), tw in zip(post, twin_recons):
        assert np.array_equal(plane, tw)
    # and the first post-switch plane holds strictly fewer live beams
    # than the dense cache had (the old ring is GONE, not overlaid)
    pre_plane = eng.recon_history[0][switch_at - 1][0]
    assert (post[0][0] != RECON_EMPTY).sum() < (
        pre_plane != RECON_EMPTY
    ).sum()


def test_snapshot_restore_continuation():
    """Whole-fleet snapshot -> restore into a fresh engine continues
    the reconstruction bit-exactly (the ring is state, not cache)."""
    from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest

    params = _params(fleet_ingest_backend="fused")
    frames = _dense_frames(4, drift_per_rev=60.0)
    run = 4
    chunks = _chunks(frames, run)
    half = len(chunks) // 2

    def ticks_of(chs, t0):
        t = [t0]
        out = []
        for ch in chs:
            batch = []
            for f in ch:
                t[0] += 0.00125
                batch.append((f, t[0]))
            out.append([(ANS, batch)])
        return out

    def fresh():
        e = FleetFusedIngest(params, 1, beams=BEAMS, capacity=1024,
                             max_revs=2, buckets=(run,))
        e.recon_log = True
        return e

    ref = fresh()
    ref.submit_backlog(ticks_of(chunks, 100.0))

    a = fresh()
    a.submit_backlog(ticks_of(chunks[:half], 100.0))
    snap = a.snapshot()
    assert any(k == "ingest.recon_ring" for k in snap)
    b = fresh()
    assert b.restore(snap)
    b.recon_history = [[]]
    b.submit_backlog(
        ticks_of(chunks[half:], 100.0 + 0.00125 * half * run)
    )
    tail = ref.recon_history[0][-len(b.recon_history[0]):]
    assert len(b.recon_history[0]) > 0
    for (pb, _xb), (pr, _xr) in zip(b.recon_history[0], tail):
        assert np.array_equal(pb, pr)
    # a deskew-off snapshot must be rejected by a deskew-on engine
    # (ingest plane mismatch), state untouched
    off = FleetFusedIngest(
        DriverParams(
            filter_chain=("clip", "median", "voxel"), filter_window=4,
            voxel_grid_size=32, filter_backend="cpu",
            fleet_ingest_backend="fused",
        ),
        1, beams=BEAMS, capacity=1024, max_revs=2, buckets=(run,),
    )
    off.submit_backlog(ticks_of(chunks[:2], 100.0))
    assert not fresh().restore(off.snapshot())


def test_stream_snapshot_roundtrip_and_rejoin_reset():
    """Per-stream snapshot/restore (the quarantine checkpoint / shard
    migration unit): ``restore_decode=True`` continues the ring
    bit-exactly; the DEFAULT rejoin path resets it with the decode
    carries — the cache restarts with the engines."""
    from rplidar_ros2_driver_tpu.driver.ingest import (
        INGEST_STREAM_SNAPSHOT_VERSION,
        FleetFusedIngest,
    )

    params = _params(fleet_ingest_backend="fused")
    frames = _dense_frames(4, drift_per_rev=60.0)
    run = 4
    chunks = _chunks(frames, run)
    half = len(chunks) // 2

    def ticks_of(chs, t0):
        t = [t0]
        out = []
        for ch in chs:
            batch = []
            for f in ch:
                t[0] += 0.00125
                batch.append((f, t[0]))
            out.append([(ANS, batch)])
        return out

    def fresh():
        e = FleetFusedIngest(params, 1, beams=BEAMS, capacity=1024,
                             max_revs=2, buckets=(run,))
        e.recon_log = True
        return e

    ref = fresh()
    ref.submit_backlog(ticks_of(chunks, 100.0))

    a = fresh()
    a.submit_backlog(ticks_of(chunks[:half], 100.0))
    snap = a.snapshot_stream(0)
    # v3 = the PR 13 carry layout (optional in-program map rows join
    # the key space); this deskew-only snapshot carries the v2 keys
    # under the v3 stamp
    assert int(snap["version"]) == INGEST_STREAM_SNAPSHOT_VERSION == 3
    assert "ingest.recon_ring" in snap

    # migration-style restore: decode rows included -> bit-exact tail
    b = fresh()
    assert b.restore_stream(0, snap, restore_decode=True)
    b.recon_history = [[]]
    b.submit_backlog(ticks_of(chunks[half:], 100.0 + 0.00125 * half * run))
    tail = ref.recon_history[0][-len(b.recon_history[0]):]
    for (pb, _xb), (pr, _xr) in zip(b.recon_history[0], tail):
        assert np.array_equal(pb, pr)

    # rejoin-style restore (default): decode carries + ring reset — the
    # first reconstruction afterwards is a FRESH twin's, not a stitched
    # continuation of the pre-quarantine cache
    c = fresh()
    c.submit_backlog(ticks_of(chunks[:half], 100.0))
    assert c.restore_stream(0, snap)
    c.recon_history = [[]]
    c.submit_backlog(ticks_of(chunks[half:], 500.0))
    twin = DeskewHostTwin(
        deskew_config_from_params(params, BEAMS), max_nodes=1024
    )
    t = [500.0]
    twin_recons = []
    for ch in chunks[half:]:
        items = []
        for f in ch:
            t[0] += 0.00125
            items.append((f, t[0]))
        combined, pushed, _revs = twin.tick(ANS, items)
        if pushed:
            twin_recons.append(combined)
    assert len(c.recon_history[0]) == len(twin_recons) > 0
    for (pc, _xc), tw in zip(c.recon_history[0], twin_recons):
        assert np.array_equal(pc, tw)

    # version skew is rejected with state untouched
    bad = dict(snap)
    bad["version"] = np.asarray(1, np.int32)
    assert not fresh().restore_stream(0, bad)


def test_meta_and_result_arity():
    from rplidar_ros2_driver_tpu.ops.filters import FilterConfig
    from rplidar_ros2_driver_tpu.ops.ingest import (
        ingest_config_for,
        ingest_meta_len,
    )
    from rplidar_ros2_driver_tpu.protocol import timing as timingmod

    fcfg = FilterConfig(window=4, beams=BEAMS, grid=32)
    base = ingest_config_for(ANS, timingmod.TimingDesc(), fcfg, max_revs=2)
    dsk = ingest_config_for(
        ANS, timingmod.TimingDesc(), fcfg, max_revs=2, deskew=DSK
    )
    assert ingest_meta_len(dsk) == ingest_meta_len(base) + 5
    # and the result tuple grows by exactly the two recon planes
    outs, _st, _cfg = _feed_single(DSK, _dense_frames(2))
    assert outs[0].recon_plane is not None
    assert outs[0].recon_pts is not None
    outs2, _st2, _cfg2 = _feed_single(None, _dense_frames(2))
    assert outs2[0].recon_plane is None


def test_rasterize_clip_mirrors_chain_enable_clip():
    """The rasterizer's clip fold follows the CHAIN's clip stage: with
    'clip' absent from filter_chain the reconstruction keeps the
    out-of-range returns the filter keeps (review-driven — the
    'reconstructed sweep keeps exactly the returns the filter keeps'
    contract must hold in both directions)."""
    angle = np.asarray([100, 20000], np.int32)
    dist = np.asarray([45 * 4000, 8000], np.int32)  # 45 m: beyond clip
    quality = np.asarray([50, 50], np.int32)
    valid = np.ones(2, bool)
    clip_on = deskew_config_from_params(_params(), BEAMS)
    clip_off = deskew_config_from_params(
        _params(filter_chain=("median", "voxel")), BEAMS
    )
    assert clip_on.enable_clip and not clip_off.enable_clip
    s_on = rasterize_subsweep_np(angle, dist, quality, valid, clip_on)
    s_off = rasterize_subsweep_np(angle, dist, quality, valid, clip_off)
    assert (s_on != RECON_EMPTY).sum() == 1   # 45 m return clipped
    assert (s_off != RECON_EMPTY).sum() == 2  # kept, like the filter
    # jnp twin agrees on both configs
    for c in (clip_on, clip_off):
        assert np.array_equal(
            np.asarray(rasterize_subsweep(angle, dist, quality, valid, c)),
            rasterize_subsweep_np(angle, dist, quality, valid, c),
        )


def test_restore_stream_rejects_deskew_off_snapshot():
    """A deskew-off per-stream snapshot must be REJECTED by a deskew-on
    engine's migration restore (restore_decode=True): silently skipping
    the missing planes would leave the lane's previous occupant's
    sub-sweep cache attributed to the migrated stream (review-driven)."""
    from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest

    run = 4
    frames = _dense_frames(2)

    def ticks_of(chs, t0):
        t = [t0]
        out = []
        for ch in chs:
            batch = []
            for f in ch:
                t[0] += 0.00125
                batch.append((f, t[0]))
            out.append([(ANS, batch)])
        return out

    off = FleetFusedIngest(
        DriverParams(
            filter_chain=("clip", "median", "voxel"), filter_window=4,
            voxel_grid_size=32, filter_backend="cpu",
            fleet_ingest_backend="fused",
        ),
        1, beams=BEAMS, capacity=1024, max_revs=2, buckets=(run,),
    )
    off.submit_backlog(ticks_of(_chunks(frames, run)[:2], 100.0))
    snap_off = off.snapshot_stream(0)

    on = FleetFusedIngest(
        _params(fleet_ingest_backend="fused"), 1, beams=BEAMS,
        capacity=1024, max_revs=2, buckets=(run,),
    )
    on.submit_backlog(ticks_of(_chunks(frames, run)[:2], 100.0))
    assert not on.restore_stream(0, snap_off, restore_decode=True)
    # and the symmetric direction: deskew-on snapshot into a deskew-off
    # engine is rejected too
    snap_on = on.snapshot_stream(0)
    assert not off.restore_stream(0, snap_on, restore_decode=True)


def test_idle_tick_clears_last_poses():
    """An all-idle tick through the recon mapper seam clears last_poses
    (review-driven: the stash must never republish the previous tick's
    poses as current, matching the per-revolution seam's overwrite)."""
    from rplidar_ros2_driver_tpu.parallel.service import (
        ShardedFilterService,
    )

    params = _params(
        fleet_ingest_backend="fused",
        map_enable=True, map_backend="host", map_grid=64, map_cell_m=0.1,
    )
    svc = ShardedFilterService(
        params, 2, beams=BEAMS, capacity=1024, fleet_ingest_buckets=(4,)
    )
    svc._ensure_byte_ingest()
    mapper = svc.attach_mapper()
    frames = _dense_frames(2)
    t = [100.0]
    for ch in _chunks(frames, 4):
        batch = []
        for f in ch:
            t[0] += 0.00125
            batch.append((f, t[0]))
        svc.submit_bytes([(ANS, batch), (ANS, list(batch))])
    assert any(p is not None for p in svc.last_poses)
    svc.submit_bytes([None, None])  # idle tick: nothing fresh
    assert all(p is None for p in svc.last_poses)
    assert mapper.matches >= 0  # mapper untouched by the idle tick


def test_active_host_seam_refuses_deskew():
    """The validator can only see the param FIELDS; the seams that know
    their ACTIVE backend refuse deskew_enable loudly instead of
    silently building skew-uncorrected maps (review-driven): a service
    whose fleet backend resolved host, and a node whose ingest seam
    resolved host, both raise."""
    from rplidar_ros2_driver_tpu.node.node import RPlidarNode
    from rplidar_ros2_driver_tpu.parallel.service import (
        ShardedFilterService,
    )

    # passes validate() — 'fused' is spelled into the OTHER seam
    params = _params(
        ingest_backend="fused", fleet_ingest_backend="host"
    )
    params.validate()
    svc = ShardedFilterService(
        params, 2, beams=BEAMS, capacity=1024
    )
    with pytest.raises(ValueError, match="fused fleet ingest backend"):
        svc._ensure_byte_ingest()

    node_params = _params(
        ingest_backend="host", fleet_ingest_backend="fused",
        dummy_mode=True,
    )
    node_params.validate()
    node = RPlidarNode(node_params)
    with pytest.raises(ValueError, match="resolve fused"):
        node._resolve_fused_ingest()


def test_recon_points_decode_matches_filters():
    """The reconstructed sweep's f32 decode is the chain's own helpers:
    a plane pushed through ops/deskew.recon_points equals _grid_decode
    + polar_to_cartesian applied directly."""
    import jax.numpy as jnp

    from rplidar_ros2_driver_tpu.ops.deskew import recon_points
    from rplidar_ros2_driver_tpu.ops.filters import (
        _grid_decode,
        polar_to_cartesian,
    )

    rng = np.random.default_rng(9)
    angle, dist, quality, valid = _rand_nodes(rng)
    plane = rasterize_subsweep_np(angle, dist, quality, valid, DSK)
    ranges, xy, mask = recon_points(jnp.asarray(plane))
    r2, _i2 = _grid_decode(jnp.asarray(plane))
    xy2, m2 = polar_to_cartesian(r2, BEAMS)
    assert np.array_equal(np.asarray(ranges), np.asarray(r2))
    assert np.array_equal(np.asarray(xy), np.asarray(xy2))
    assert np.array_equal(np.asarray(mask), np.asarray(m2))
