"""Pallas temporal-median kernel vs the XLA reference (interpret mode on CPU)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from rplidar_ros2_driver_tpu.ops.filters import (
    FilterConfig,
    FilterState,
    filter_step,
    temporal_median,
)
from rplidar_ros2_driver_tpu.ops.pallas_kernels import temporal_median_pallas


def rand_window(rng, w, b, inf_frac=0.3):
    win = rng.uniform(0.1, 40.0, (w, b)).astype(np.float32)
    win[rng.uniform(size=(w, b)) < inf_frac] = np.inf
    return win


@pytest.mark.parametrize(
    "w,b",
    [(1, 5), (2, 128), (4, 16), (7, 100), (16, 640), (64, 2048), (33, 257)],
)
def test_matches_xla_reference(w, b):
    rng = np.random.default_rng(w * 1000 + b)
    win = rand_window(rng, w, b)
    win[:, 0] = np.inf  # an all-missing beam stays +inf
    ref = np.asarray(temporal_median(jnp.asarray(win)))
    got = np.asarray(temporal_median_pallas(jnp.asarray(win)))
    np.testing.assert_array_equal(ref, got)


def test_lowering_dispatch_matches_pinned_interpret():
    """interpret=None resolves per LOWERING platform (lax.platform_dependent,
    r4 ADVICE): on the CPU test backend the dispatched result must be
    bit-identical to an explicitly pinned interpret=True call, both
    eagerly and under an outer jit, for all three entry points."""
    import jax

    from rplidar_ros2_driver_tpu.ops.pallas_kernels import (
        sliding_median_pallas,
        sorted_replace_pallas,
    )

    rng = np.random.default_rng(42)
    win = rand_window(rng, 8, 130)

    auto = np.asarray(temporal_median_pallas(jnp.asarray(win)))
    pinned = np.asarray(temporal_median_pallas(jnp.asarray(win), interpret=True))
    np.testing.assert_array_equal(auto, pinned)
    jitted = np.asarray(
        jax.jit(lambda x: temporal_median_pallas(x))(jnp.asarray(win))
    )
    np.testing.assert_array_equal(jitted, pinned)

    ext = rand_window(rng, 8 + 16, 130)
    np.testing.assert_array_equal(
        np.asarray(sliding_median_pallas(jnp.asarray(ext), 8)),
        np.asarray(sliding_median_pallas(jnp.asarray(ext), 8, interpret=True)),
    )

    s = np.sort(rand_window(rng, 8, 130, inf_frac=0.2), axis=0)
    old = s[3].copy()
    new = rng.uniform(0.1, 40.0, 130).astype(np.float32)
    out_a, med_a = sorted_replace_pallas(
        jnp.asarray(s), jnp.asarray(old), jnp.asarray(new)
    )
    out_p, med_p = sorted_replace_pallas(
        jnp.asarray(s), jnp.asarray(old), jnp.asarray(new), interpret=True
    )
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_p))
    np.testing.assert_array_equal(np.asarray(med_a), np.asarray(med_p))


def test_all_finite_window_is_exact_lower_median():
    rng = np.random.default_rng(7)
    win = rand_window(rng, 8, 64, inf_frac=0.0)
    got = np.asarray(temporal_median_pallas(jnp.asarray(win)))
    want = np.sort(win, axis=0)[(8 - 1) // 2]
    np.testing.assert_array_equal(got, want)


def test_filter_step_pallas_backend_matches_xla():
    from rplidar_ros2_driver_tpu.driver.dummy import synth_scan

    cfg_x = FilterConfig(window=8, beams=256, grid=32, cell_m=0.5)
    cfg_p = dataclasses.replace(cfg_x, median_backend="pallas")
    sx = FilterState.create(8, 256, 32)
    sp = FilterState.create(8, 256, 32)
    for k in range(10):
        batch = synth_scan(jnp.float32(0.1 * k), count=360, capacity=512)
        sx, ox = filter_step(sx, batch, cfg_x)
        sp, op = filter_step(sp, batch, cfg_p)
    np.testing.assert_array_equal(np.asarray(ox.ranges), np.asarray(op.ranges))
    np.testing.assert_array_equal(np.asarray(ox.voxel), np.asarray(op.voxel))


@pytest.mark.parametrize(
    "w,k,b",
    [(4, 8, 64), (6, 8, 100), (7, 16, 257), (8, 3, 128), (16, 24, 640), (1, 8, 32)],
)
def test_sliding_median_matches_successive_windows(w, k, b):
    """sliding_median_pallas over a (W+K, B) stripe must equal K separate
    temporal_median calls on the advancing windows — including non-power-
    of-two W (in-kernel +inf pad rows) and k not a multiple of 8 (stripe
    pad + output slice)."""
    from rplidar_ros2_driver_tpu.ops.pallas_kernels import sliding_median_pallas

    rng = np.random.default_rng(w * 100 + k * 10 + b)
    ext = rand_window(rng, w + k, b)
    got = np.asarray(sliding_median_pallas(jnp.asarray(ext), w))
    want = np.stack(
        [np.asarray(temporal_median(jnp.asarray(ext[i + 1 : i + 1 + w]))) for i in range(k)]
    )
    np.testing.assert_array_equal(got, want)


class TestSortedReplacePallas:
    """The fused VMEM sorted_replace kernel vs the jnp formulation —
    the two lowerings of median_backend='inc' must be bit-exact."""

    @pytest.mark.parametrize("w,b", [(4, 16), (8, 64), (16, 640), (7, 100)])
    def test_matches_jnp_formulation(self, w, b):
        from rplidar_ros2_driver_tpu.ops.filters import (
            median_from_sorted,
            sorted_replace,
        )
        from rplidar_ros2_driver_tpu.ops.pallas_kernels import (
            sorted_replace_pallas,
        )

        rng = np.random.default_rng(w * 77 + b)
        ring = np.full((w, b), np.inf, np.float32)
        sor = np.sort(ring, axis=0)
        cursor = 0
        for step in range(3 * w + 5):
            new = rng.uniform(0.1, 40.0, b).astype(np.float32)
            new[rng.random(b) < 0.3] = np.inf          # missing returns
            if step % 5 == 0:
                new[:] = new[0]                         # heavy ties
            old = ring[cursor].copy()
            ref = np.asarray(
                sorted_replace(
                    jnp.asarray(sor), jnp.asarray(old), jnp.asarray(new)
                )
            )
            ref_med = np.asarray(median_from_sorted(jnp.asarray(ref)))
            got, got_med = sorted_replace_pallas(
                jnp.asarray(sor), jnp.asarray(old), jnp.asarray(new)
            )
            np.testing.assert_array_equal(np.asarray(got), ref)
            np.testing.assert_array_equal(np.asarray(got_med), ref_med)
            sor = ref
            ring[cursor] = new
            cursor = (cursor + 1) % w

    def test_full_step_parity_inc_pallas_vs_inc_xla(self):
        """Whole-step trajectories under the two pinned inc lowerings
        are bit-identical, through unfilled windows AND wraparound."""
        from rplidar_ros2_driver_tpu.ops import filters

        rng = np.random.default_rng(11)
        cfgs = {
            b: FilterConfig(
                window=6, beams=64, grid=32, cell_m=0.25, median_backend=b,
            )
            for b in ("inc_xla", "inc_pallas")
        }
        states = {b: FilterState.for_config(c) for b, c in cfgs.items()}
        for step in range(15):
            n = 300
            angle = np.sort(
                rng.integers(0, 1 << 14, n).astype(np.int32)
            )
            dist = rng.integers(0, 16000, n).astype(np.int32)
            qual = rng.integers(0, 255, n).astype(np.int32)
            outs = {}
            for b, c in cfgs.items():
                buf = filters.pack_host_scan_counted(
                    angle, dist, qual, None, 512
                )
                states[b], outs[b] = filters.counted_filter_step(
                    states[b], jnp.asarray(buf), c
                )
            np.testing.assert_array_equal(
                np.asarray(outs["inc_xla"].ranges),
                np.asarray(outs["inc_pallas"].ranges),
            )
            np.testing.assert_array_equal(
                np.asarray(outs["inc_xla"].voxel),
                np.asarray(outs["inc_pallas"].voxel),
            )
