"""Pallas temporal-median kernel vs the XLA reference (interpret mode on CPU)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from rplidar_ros2_driver_tpu.ops.filters import (
    FilterConfig,
    FilterState,
    filter_step,
    temporal_median,
)
from rplidar_ros2_driver_tpu.ops.pallas_kernels import temporal_median_pallas


def rand_window(rng, w, b, inf_frac=0.3):
    win = rng.uniform(0.1, 40.0, (w, b)).astype(np.float32)
    win[rng.uniform(size=(w, b)) < inf_frac] = np.inf
    return win


@pytest.mark.parametrize(
    "w,b",
    [(1, 5), (2, 128), (4, 16), (7, 100), (16, 640), (64, 2048), (33, 257)],
)
def test_matches_xla_reference(w, b):
    rng = np.random.default_rng(w * 1000 + b)
    win = rand_window(rng, w, b)
    win[:, 0] = np.inf  # an all-missing beam stays +inf
    ref = np.asarray(temporal_median(jnp.asarray(win)))
    got = np.asarray(temporal_median_pallas(jnp.asarray(win)))
    np.testing.assert_array_equal(ref, got)


def test_all_finite_window_is_exact_lower_median():
    rng = np.random.default_rng(7)
    win = rand_window(rng, 8, 64, inf_frac=0.0)
    got = np.asarray(temporal_median_pallas(jnp.asarray(win)))
    want = np.sort(win, axis=0)[(8 - 1) // 2]
    np.testing.assert_array_equal(got, want)


def test_filter_step_pallas_backend_matches_xla():
    from rplidar_ros2_driver_tpu.driver.dummy import synth_scan

    cfg_x = FilterConfig(window=8, beams=256, grid=32, cell_m=0.5)
    cfg_p = dataclasses.replace(cfg_x, median_backend="pallas")
    sx = FilterState.create(8, 256, 32)
    sp = FilterState.create(8, 256, 32)
    for k in range(10):
        batch = synth_scan(jnp.float32(0.1 * k), count=360, capacity=512)
        sx, ox = filter_step(sx, batch, cfg_x)
        sp, op = filter_step(sp, batch, cfg_p)
    np.testing.assert_array_equal(np.asarray(ox.ranges), np.asarray(op.ranges))
    np.testing.assert_array_equal(np.asarray(ox.voxel), np.asarray(op.voxel))


@pytest.mark.parametrize(
    "w,k,b",
    [(4, 8, 64), (6, 8, 100), (7, 16, 257), (8, 3, 128), (16, 24, 640), (1, 8, 32)],
)
def test_sliding_median_matches_successive_windows(w, k, b):
    """sliding_median_pallas over a (W+K, B) stripe must equal K separate
    temporal_median calls on the advancing windows — including non-power-
    of-two W (in-kernel +inf pad rows) and k not a multiple of 8 (stripe
    pad + output slice)."""
    from rplidar_ros2_driver_tpu.ops.pallas_kernels import sliding_median_pallas

    rng = np.random.default_rng(w * 100 + k * 10 + b)
    ext = rand_window(rng, w + k, b)
    got = np.asarray(sliding_median_pallas(jnp.asarray(ext), w))
    want = np.stack(
        [np.asarray(temporal_median(jnp.asarray(ext[i + 1 : i + 1 + w]))) for i in range(k)]
    )
    np.testing.assert_array_equal(got, want)
