"""Packed one-transfer ingest must match the ScanBatch path bit-for-bit."""

import jax.numpy as jnp
import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.core.types import ScanBatch
from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain
from rplidar_ros2_driver_tpu.ops.filters import (
    FilterConfig,
    FilterState,
    compact_filter_scan,
    compact_filter_step,
    counted_filter_step,
    filter_step,
    pack_host_scan,
    pack_host_scan_compact,
    pack_host_scan_counted,
    pack_host_scans_compact,
    packed_filter_step,
)


def _raw_scan(k, points=500):
    rng = np.random.default_rng(k)
    angle = ((np.arange(points) * 65536) // points).astype(np.int32)
    dist = (rng.uniform(0.2, 10.0, points) * 4000).astype(np.int32)
    qual = np.full(points, 190, np.int32)
    return angle, dist, qual


def test_packed_step_matches_scanbatch_step():
    cfg = FilterConfig(window=4, beams=128, grid=32, cell_m=0.5)
    s_a = FilterState.create(cfg.window, cfg.beams, cfg.grid)
    s_b = FilterState.create(cfg.window, cfg.beams, cfg.grid)
    for k in range(6):
        angle, dist, qual = _raw_scan(k)
        batch = ScanBatch.from_numpy(angle, dist, qual, n=1024)
        s_a, out_a = filter_step(s_a, batch, cfg)
        buf, count = pack_host_scan(angle, dist, qual, n=1024)
        s_b, out_b = packed_filter_step(s_b, buf, jnp.asarray(count, jnp.int32), cfg)
        np.testing.assert_array_equal(np.asarray(out_a.ranges), np.asarray(out_b.ranges))
        np.testing.assert_array_equal(np.asarray(out_a.voxel), np.asarray(out_b.voxel))
    np.testing.assert_array_equal(np.asarray(s_a.voxel_acc), np.asarray(s_b.voxel_acc))


def test_chain_process_raw_matches_process():
    params = DriverParams(
        filter_backend="cpu",
        filter_window=4,
        filter_chain=("clip", "median", "voxel"),
        voxel_grid_size=32,
    )
    c_a = ScanFilterChain(params, beams=128)
    c_b = ScanFilterChain(params, beams=128)
    for k in range(5):
        angle, dist, qual = _raw_scan(k + 100)
        out_a = c_a.process(ScanBatch.from_numpy(angle, dist, qual))
        out_b = c_b.process_raw(angle, dist, qual)
        np.testing.assert_array_equal(np.asarray(out_a.ranges), np.asarray(out_b.ranges))
        np.testing.assert_array_equal(np.asarray(out_a.voxel), np.asarray(out_b.voxel))


def test_chain_pipelined_is_sync_shifted_by_one():
    """The pipelined publish seam returns exactly the synchronous path's
    outputs delayed by one revolution (bounded staleness of 1), and
    flush_pipelined drains the final in-flight output."""
    params = DriverParams(
        filter_backend="cpu",
        filter_window=4,
        filter_chain=("clip", "median", "voxel"),
        voxel_grid_size=32,
    )
    c_sync = ScanFilterChain(params, beams=128)
    c_pipe = ScanFilterChain(params, beams=128)
    sync_outs, pipe_outs = [], []
    for k in range(5):
        angle, dist, qual = _raw_scan(k + 200)
        sync_outs.append(c_sync.process_raw(angle, dist, qual))
        pipe_outs.append(c_pipe.process_raw_pipelined(angle, dist, qual))
    assert pipe_outs[0] is None
    for k in range(1, 5):
        np.testing.assert_array_equal(
            np.asarray(pipe_outs[k].ranges), np.asarray(sync_outs[k - 1].ranges)
        )
        np.testing.assert_array_equal(
            np.asarray(pipe_outs[k].voxel), np.asarray(sync_outs[k - 1].voxel)
        )
    tail = c_pipe.flush_pipelined()
    np.testing.assert_array_equal(
        np.asarray(tail.ranges), np.asarray(sync_outs[4].ranges)
    )
    assert c_pipe.flush_pipelined() is None  # drained
    # latency-attribution diagnostics populated every tick (the e2e
    # artifact splits the publish tail into collect-wait /
    # upload+dispatch / host-side pack from exactly these): flush does
    # not dispatch, so a nonzero value proves the LAST pipelined tick
    # set it; the collect-wait assert poisons the attribute first so it
    # cannot pass on the 0.0 initializer alone
    assert c_pipe.last_upload_dispatch_s > 0.0
    c_pipe.last_collect_wait_s = -1.0
    angle, dist, qual = _raw_scan(999)
    c_pipe.process_raw_pipelined(angle, dist, qual)
    assert c_pipe.last_collect_wait_s == 0.0  # nothing pending: reset, no wait
    c_pipe.process_raw_pipelined(angle, dist, qual)
    assert c_pipe.last_collect_wait_s > 0.0  # collected a pending output


def test_chain_capacity_truncates_oversized_revolution():
    """A revolution exceeding the chain's wire capacity is truncated
    head-keep (the assembler's overflow policy) instead of raising out
    of the scan thread; the result matches the pre-truncated scan, and
    the capacity-capped warmup compile covers the capped shape."""
    params = DriverParams(
        filter_backend="cpu",
        filter_window=4,
        filter_chain=("clip", "median", "voxel"),
        voxel_grid_size=32,
    )
    cap = 256
    chain = ScanFilterChain(params, beams=128, capacity=cap)
    ref = ScanFilterChain(params, beams=128, capacity=cap)
    angle, dist, qual = _raw_scan(42, points=cap + 60)
    out = chain.process_raw(angle, dist, qual)
    out_ref = ref.process_raw(angle[:cap], dist[:cap], qual[:cap])
    np.testing.assert_array_equal(np.asarray(out.ranges), np.asarray(out_ref.ranges))
    # pipelined path truncates identically
    assert chain.process_raw_pipelined(angle, dist, qual) is None


def test_chain_pipelined_dispatch_failure_keeps_pending(monkeypatch):
    """If revolution N's upload/dispatch fails after N-1 was popped, the
    pending wire must be re-stashed so the drain can still publish N-1
    (a transient link fault must not silently lose a revolution)."""
    import rplidar_ros2_driver_tpu.filters.chain as chain_mod

    params = DriverParams(
        filter_backend="cpu",
        filter_window=4,
        filter_chain=("clip", "median", "voxel"),
        voxel_grid_size=32,
    )
    chain = ScanFilterChain(params, beams=128)
    ref = ScanFilterChain(params, beams=128)
    a1, d1, q1 = _raw_scan(400)
    assert chain.process_raw_pipelined(a1, d1, q1) is None
    ref_out = ref.process_raw(a1, d1, q1)

    def boom(*a, **k):
        raise RuntimeError("link died")

    monkeypatch.setattr(chain_mod, "counted_filter_step_wire", boom)
    a2, d2, q2 = _raw_scan(401)
    with pytest.raises(RuntimeError):
        chain.process_raw_pipelined(a2, d2, q2)
    monkeypatch.undo()
    tail = chain.flush_pipelined()
    assert tail is not None
    np.testing.assert_array_equal(
        np.asarray(tail.ranges), np.asarray(ref_out.ranges)
    )


def test_chain_pipelined_fetch_failure_keeps_pending(monkeypatch):
    """If the device->host fetch of N-1 itself fails (the same transient
    link fault class as a dispatch failure), the pending wire must be
    re-stashed so a later drain can retry the fetch — not dropped."""
    import rplidar_ros2_driver_tpu.filters.chain as chain_mod

    params = DriverParams(
        filter_backend="cpu",
        filter_window=4,
        filter_chain=("clip", "median", "voxel"),
        voxel_grid_size=32,
    )
    chain = ScanFilterChain(params, beams=128)
    ref = ScanFilterChain(params, beams=128)
    a1, d1, q1 = _raw_scan(410)
    assert chain.process_raw_pipelined(a1, d1, q1) is None
    ref_out = ref.process_raw(a1, d1, q1)

    def boom(*a, **k):
        raise RuntimeError("fetch died")

    monkeypatch.setattr(chain_mod, "unpack_output_wire", boom)
    a2, d2, q2 = _raw_scan(411)
    with pytest.raises(RuntimeError):
        chain.process_raw_pipelined(a2, d2, q2)
    monkeypatch.undo()
    tail = chain.flush_pipelined()
    assert tail is not None
    np.testing.assert_array_equal(
        np.asarray(tail.ranges), np.asarray(ref_out.ranges)
    )


def test_chain_pipelined_reset_drops_pending():
    """A reset/restore must clear the in-flight output: pre-reset data
    must never be published into the post-reset stream."""
    params = DriverParams(
        filter_backend="cpu",
        filter_window=4,
        filter_chain=("clip", "median", "voxel"),
        voxel_grid_size=32,
    )
    chain = ScanFilterChain(params, beams=128)
    angle, dist, qual = _raw_scan(300)
    assert chain.process_raw_pipelined(angle, dist, qual) is None
    chain.reset()
    assert chain.flush_pipelined() is None
    assert chain.process_raw_pipelined(angle, dist, qual) is None


def test_compact_step_matches_scanbatch_step():
    """The 6-byte/point bit-packed wire form must be lossless for
        in-range values (18-bit distances, 6-bit flags)."""
    cfg = FilterConfig(window=4, beams=128, grid=32, cell_m=0.5)
    s_a = FilterState.create(cfg.window, cfg.beams, cfg.grid)
    s_b = FilterState.create(cfg.window, cfg.beams, cfg.grid)
    for k in range(6):
        angle, dist, qual = _raw_scan(k)
        flag = np.zeros(len(angle), np.int32)
        flag[0] = 1
        batch = ScanBatch.from_numpy(angle, dist, qual, flag, n=1024)
        s_a, out_a = filter_step(s_a, batch, cfg)
        buf, count = pack_host_scan_compact(angle, dist, qual, flag, n=1024)
        assert buf.dtype == np.uint16 and buf.shape == (3, 1024)
        s_b, out_b = compact_filter_step(s_b, buf, jnp.asarray(count, jnp.int32), cfg)
        np.testing.assert_array_equal(np.asarray(out_a.ranges), np.asarray(out_b.ranges))
        np.testing.assert_array_equal(np.asarray(out_a.voxel), np.asarray(out_b.voxel))
    np.testing.assert_array_equal(np.asarray(s_a.voxel_acc), np.asarray(s_b.voxel_acc))


def test_counted_step_matches_compact_step():
    """The count-embedded one-transfer form must match buffer+scalar exactly."""
    cfg = FilterConfig(window=4, beams=128, grid=32, cell_m=0.5)
    s_a = FilterState.create(cfg.window, cfg.beams, cfg.grid)
    s_b = FilterState.create(cfg.window, cfg.beams, cfg.grid)
    for k in range(6):
        angle, dist, qual = _raw_scan(k, points=500 + 3 * k)
        flag = np.zeros(len(angle), np.int32)
        flag[0] = 1
        buf, count = pack_host_scan_compact(angle, dist, qual, flag, n=1024)
        s_a, out_a = compact_filter_step(s_a, buf, jnp.asarray(count, jnp.int32), cfg)
        cbuf = pack_host_scan_counted(angle, dist, qual, flag, n=1024)
        assert int(cbuf[0, -1]) == count
        s_b, out_b = counted_filter_step(s_b, cbuf, cfg)
        np.testing.assert_array_equal(np.asarray(out_a.ranges), np.asarray(out_b.ranges))
        np.testing.assert_array_equal(np.asarray(out_a.voxel), np.asarray(out_b.voxel))
    np.testing.assert_array_equal(np.asarray(s_a.voxel_acc), np.asarray(s_b.voxel_acc))


def test_counted_pack_keeps_full_capacity():
    """The count rides in an extra column, so a revolution filling the
    buffer exactly (the assembler's MAX_SCAN_NODES truncation case)
    keeps every node — no silent drop vs the compact form."""
    angle = np.arange(1024, dtype=np.int32)
    buf = pack_host_scan_counted(angle, angle, angle, n=1024)
    assert buf.shape == (3, 1025)
    assert int(buf[0, -1]) == 1024
    np.testing.assert_array_equal(buf[1, :1024].astype(np.int64), angle)
    # over capacity still rejects (same contract as the compact form)
    import pytest

    big = np.zeros(2048, np.int32)
    with pytest.raises(ValueError):
        pack_host_scan_counted(big, big, big, n=1024)


def test_compact_roundtrip_field_ranges():
    """Boundary values of every field survive the 6-byte bit packing
    (distance clamps at 18 bits = 65.5 m, flag at 6 bits — documented
    in _pack_compact_rows; both beyond any real device's range)."""
    from rplidar_ros2_driver_tpu.ops.filters import _unpack_compact

    angle = np.array([0, 1, 65535, 7], np.int32)
    dist = np.array([0, 123456, 0x3FFFF, 0x7FFFFFFF], np.int32)
    qual = np.array([0, 128, 255, 9], np.int32)
    flag = np.array([1, 0, 63, 2], np.int32)
    buf, count = pack_host_scan_compact(angle, dist, qual, flag, n=8)
    assert buf.shape == (3, 8) and buf.dtype == np.uint16
    batch = _unpack_compact(jnp.asarray(buf), jnp.asarray(count, jnp.int32))
    np.testing.assert_array_equal(np.asarray(batch.angle_q14)[:4], angle)
    np.testing.assert_array_equal(np.asarray(batch.quality)[:4], qual)
    np.testing.assert_array_equal(np.asarray(batch.flag)[:4], flag)
    # 18-bit distances round-trip exactly; larger clamp to the max
    np.testing.assert_array_equal(
        np.asarray(batch.dist_q2)[:4], np.minimum(dist, 0x3FFFF)
    )


def test_dense_step_resample_matches_scatter():
    """The streaming step's dense-tile resampler (resample_backend=
    "dense", the fused path's formulation at K=1) must be bit-identical
    to the scatter-min default across a multi-step trajectory."""
    base = dict(window=4, beams=128, grid=32, cell_m=0.5)
    cfg_s = FilterConfig(**base)
    cfg_d = FilterConfig(resample_backend="dense", **base)
    s_a = FilterState.create(4, 128, 32)
    s_b = FilterState.create(4, 128, 32)
    for k in range(6):
        angle, dist, qual = _raw_scan(k + 700)
        buf = pack_host_scan_counted(angle, dist, qual, None, 1024)
        s_a, out_a = counted_filter_step(s_a, buf, cfg_s)
        s_b, out_b = counted_filter_step(s_b, buf, cfg_d)
        np.testing.assert_array_equal(np.asarray(out_a.ranges), np.asarray(out_b.ranges))
        np.testing.assert_array_equal(
            np.asarray(out_a.intensities), np.asarray(out_b.intensities)
        )
        np.testing.assert_array_equal(np.asarray(out_a.voxel), np.asarray(out_b.voxel))
    np.testing.assert_array_equal(np.asarray(s_a.voxel_acc), np.asarray(s_b.voxel_acc))


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_fused_scan_matches_sequential_steps(backend):
    """compact_filter_scan (K scans, one dispatch) must reproduce the exact
    state trajectory and per-scan ranges of K compact_filter_step calls.

    Split across two fused calls so both chunk regimes of the parallel
    implementation are exercised: K=3 < W (old window rows survive into
    the final state, entry cursor 0) and K=10 > W (final window is all
    new rows, nonzero entry cursor with ring wrap-around)."""
    cfg = FilterConfig(window=4, beams=128, grid=32, cell_m=0.5, median_backend=backend)
    scans = []
    for k in range(13):
        angle, dist, qual = _raw_scan(k, points=300 + 20 * k)
        scans.append({"angle_q14": angle, "dist_q2": dist, "quality": qual})

    s_seq = FilterState.create(cfg.window, cfg.beams, cfg.grid)
    ranges_seq = []
    for s in scans:
        buf, count = pack_host_scan_compact(
            s["angle_q14"], s["dist_q2"], s["quality"], None, 1024
        )
        s_seq, out = compact_filter_step(s_seq, buf, jnp.asarray(count, jnp.int32), cfg)
        ranges_seq.append(np.asarray(out.ranges))

    # the parallel production path AND the lax.scan reference form must
    # both reproduce the per-step trajectory
    from rplidar_ros2_driver_tpu.ops.filters import _compact_filter_scan_sequential

    for scan_fn in (compact_filter_scan, _compact_filter_scan_sequential):
        s_fused = FilterState.create(cfg.window, cfg.beams, cfg.grid)
        fused_ranges = []
        for lo, hi in ((0, 3), (3, 13)):  # K < W, then K > W
            seq, counts = pack_host_scans_compact(scans[lo:hi], 1024)
            s_fused, ranges = scan_fn(s_fused, seq, counts, cfg)
            fused_ranges.append(np.asarray(ranges))
        np.testing.assert_array_equal(
            np.concatenate(fused_ranges), np.stack(ranges_seq)
        )
        for name in ("range_window", "inten_window", "hit_window", "voxel_acc",
                     "cursor", "filled"):
            np.testing.assert_array_equal(
                np.asarray(getattr(s_fused, name)),
                np.asarray(getattr(s_seq, name)),
                name,
            )


@pytest.mark.parametrize("k_chunk", [1, 4, 7])
def test_fused_scan_edge_regimes(k_chunk):
    """K == W, K == 1, and K coprime-to-W chunks must all reproduce the
    per-step trajectory — these hit the parallel implementation's
    boundary arithmetic (full-window replacement, single-step stripe,
    cursor positions that never revisit 0)."""
    cfg = FilterConfig(window=4, beams=128, grid=32, cell_m=0.5)
    scans = []
    for k in range(14):
        angle, dist, qual = _raw_scan(k + 40, points=260)
        # adversarial ordering: the resampler must not assume the
        # rotation-sorted layout real revolutions have
        rng = np.random.default_rng(1000 + k)
        perm = rng.permutation(len(angle))
        scans.append(
            {"angle_q14": angle[perm], "dist_q2": dist[perm], "quality": qual[perm]}
        )

    s_seq = FilterState.create(cfg.window, cfg.beams, cfg.grid)
    ranges_seq = []
    for s in scans:
        buf, count = pack_host_scan_compact(
            s["angle_q14"], s["dist_q2"], s["quality"], None, 512
        )
        s_seq, out = compact_filter_step(s_seq, buf, jnp.asarray(count, jnp.int32), cfg)
        ranges_seq.append(np.asarray(out.ranges))

    s_fused = FilterState.create(cfg.window, cfg.beams, cfg.grid)
    got = []
    for lo in range(0, 14, k_chunk):
        chunk = scans[lo : lo + k_chunk]
        if not chunk:
            break
        seq, counts = pack_host_scans_compact(chunk, 512)
        s_fused, ranges = compact_filter_scan(s_fused, seq, counts, cfg)
        got.append(np.asarray(ranges))
    np.testing.assert_array_equal(np.concatenate(got), np.stack(ranges_seq))
    for name in ("range_window", "inten_window", "hit_window", "voxel_acc",
                 "cursor", "filled"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_fused, name)), np.asarray(getattr(s_seq, name)), name
        )


def test_replay_through_chain_matches_streaming_chain():
    from rplidar_ros2_driver_tpu.replay import replay_through_chain

    params = DriverParams(
        filter_backend="cpu",
        filter_window=4,
        filter_chain=("clip", "median", "voxel"),
        voxel_grid_size=32,
    )
    scans = []
    for k in range(9):
        angle, dist, qual = _raw_scan(k + 7)
        scans.append({"angle_q14": angle, "dist_q2": dist, "quality": qual})
    chain = ScanFilterChain(params, beams=128)
    stream_ranges = [
        np.asarray(chain.process_raw(s["angle_q14"], s["dist_q2"], s["quality"]).ranges)
        for s in scans
    ]
    ranges, final_state = replay_through_chain(scans, params, beams=128, chunk=4)
    np.testing.assert_array_equal(ranges, np.stack(stream_ranges))
    np.testing.assert_array_equal(
        np.asarray(final_state.voxel_acc), np.asarray(chain.state.voxel_acc)
    )


def test_pack_host_scan_overflow():
    import pytest

    angle = np.zeros(2048, np.int32)
    with pytest.raises(ValueError):
        pack_host_scan(angle, angle, angle, n=1024)


def test_chain_warmup_is_invisible():
    """Eager precompile (warmup=True, the default) must not change any
    output: state after warmup is exactly a fresh state."""
    params = DriverParams(
        filter_backend="cpu", filter_window=4,
        filter_chain=("clip", "median", "voxel"), voxel_grid_size=32,
    )
    warm = ScanFilterChain(params, beams=128, warmup=True)
    cold = ScanFilterChain(params, beams=128, warmup=False)
    for k in range(6):
        angle, dist, qual = _raw_scan(k + 40)
        out_w = warm.process_raw(angle, dist, qual)
        out_c = cold.process_raw(angle, dist, qual)
        np.testing.assert_array_equal(np.asarray(out_w.ranges), np.asarray(out_c.ranges))
        np.testing.assert_array_equal(np.asarray(out_w.voxel), np.asarray(out_c.voxel))


def test_incompatible_snapshot_discarded():
    """Restoring a snapshot taken under different chain geometry must fall
    back to a cold start, not crash the hot path."""
    small = ScanFilterChain(
        DriverParams(filter_backend="cpu", filter_window=4,
                     filter_chain=("clip", "median"), voxel_grid_size=32),
        beams=128,
    )
    angle, dist, qual = _raw_scan(1)
    small.process_raw(angle, dist, qual)
    snap = small.snapshot()

    big = ScanFilterChain(
        DriverParams(filter_backend="cpu", filter_window=8,
                     filter_chain=("clip", "median"), voxel_grid_size=32),
        beams=128,
    )
    big.restore(snap)  # incompatible: discarded with a warning
    out = big.process_raw(angle, dist, qual)  # must not raise
    assert np.asarray(out.ranges).shape == (128,)
