"""End-to-end node tests against the dummy backend: lifecycle transitions,
the 5-state FSM, publishing, hot-plug recovery via fault injection, and
dynamic reconfigure — the automated version of the reference's manual
'unplug the cable' protocol (README.md:27-38, SURVEY.md §4)."""

import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.core.results import DeviceHealth
from rplidar_ros2_driver_tpu.driver.dummy import DummyLidarDriver
from rplidar_ros2_driver_tpu.node.fsm import DriverState, FsmTimings
from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleError, LifecycleState
from rplidar_ros2_driver_tpu.node.node import RPlidarNode, launch
from rplidar_ros2_driver_tpu.node.publisher import CollectingPublisher


def _wait(predicate, timeout=10.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def make_node(params=None, factory=None):
    params = params or DriverParams(dummy_mode=True)
    pub = CollectingPublisher()
    node = RPlidarNode(
        params,
        pub,
        driver_factory=factory or (lambda: DummyLidarDriver(scan_rate_hz=200.0)),
        fsm_timings=FsmTimings.fast(),
    )
    return node, pub


class TestLifecycle:
    def test_full_cycle(self):
        node, pub = make_node()
        assert node.lifecycle_state is LifecycleState.UNCONFIGURED
        assert node.configure()
        assert node.lifecycle_state is LifecycleState.INACTIVE
        assert node.activate()
        assert node.lifecycle_state is LifecycleState.ACTIVE
        assert _wait(lambda: pub.scan_count >= 3)
        assert node.deactivate()
        assert node.lifecycle_state is LifecycleState.INACTIVE
        assert node.cleanup()
        assert node.lifecycle_state is LifecycleState.UNCONFIGURED
        assert node.shutdown()
        assert node.lifecycle_state is LifecycleState.FINALIZED

    def test_illegal_transition_raises(self):
        node, _ = make_node()
        with pytest.raises(LifecycleError):
            node.activate()  # must configure first

    def test_tf_published_on_configure(self):
        node, pub = make_node()
        node.configure()
        assert len(pub.tf_static) == 1
        assert pub.tf_static[0].child == "laser"

    def test_launch_helper_reaches_active(self):
        node, pub = make_node()
        launch(node)
        assert node.lifecycle_state is LifecycleState.ACTIVE
        assert _wait(lambda: pub.scan_count >= 1)
        node.shutdown()


class TestScanContent:
    def test_dummy_scan_shape_and_values(self):
        node, pub = make_node()
        launch(node)
        assert _wait(lambda: pub.scan_count >= 2)
        node.shutdown()
        msg = pub.scans[-1]
        # dummy synthesizes 360 points, 2m +/- 0.5m ring
        assert len(msg.ranges) == 360
        finite = msg.ranges[np.isfinite(msg.ranges)]
        assert len(finite) == 360
        assert finite.min() > 1.4 and finite.max() < 2.6
        # dummy is not a "new type" driver, so quality 200 >> 2 == 50 —
        # same as the reference's dynamic_cast path (src/rplidar_node.cpp:585-592)
        assert (msg.intensities[np.isfinite(msg.ranges)] == 50).all()
        assert msg.range_min == pytest.approx(0.15)
        assert msg.range_max == pytest.approx(40.0)

    def test_scan_processing_mode_resamples(self):
        params = DriverParams(dummy_mode=True, scan_processing=True)
        node, pub = make_node(params)
        launch(node)
        assert _wait(lambda: pub.scan_count >= 2)
        node.shutdown()
        msg = pub.scans[-1]
        assert len(msg.ranges) == 360
        assert np.isfinite(msg.ranges).sum() > 300

    def test_pipelined_publish_matches_sync_shifted(self):
        """pipelined_publish must publish the same chain outputs as the
        synchronous seam, one revolution late, with the matching (earlier)
        stamps — and the deactivate-time drain must flush the final
        in-flight revolution rather than dropping it."""

        class TimestampingPublisher(CollectingPublisher):
            def __init__(self):
                super().__init__()
                self.pub_times = []

            def publish_scan(self, msg):
                super().publish_scan(msg)
                self.pub_times.append(time.monotonic())

        chain_kw = dict(
            dummy_mode=True,
            filter_backend="cpu",
            filter_chain=("clip", "median", "voxel"),
            filter_window=4,
            voxel_grid_size=32,
        )

        def run(params):
            pub = TimestampingPublisher()
            node = RPlidarNode(
                params, pub,
                driver_factory=lambda: DummyLidarDriver(scan_rate_hz=50.0),
                fsm_timings=FsmTimings.fast(),
            )
            launch(node)
            assert _wait(lambda: pub.scan_count >= 6)
            node.deactivate()  # pipelined: drains the in-flight revolution
            node.shutdown()
            return pub

        pub_s = run(DriverParams(**chain_kw))
        pub_p = run(DriverParams(pipelined_publish=True, **chain_kw))
        # the dummy's phase advances deterministically per revolution, so
        # scan k is identical across nodes: pipelined output k must equal
        # the synchronous output k (published one revolution later, but
        # stamped with its own revolution's time)
        n = min(pub_s.scan_count, pub_p.scan_count)
        assert n >= 5
        for k in range(n):
            np.testing.assert_array_equal(
                pub_p.scans[k].ranges, pub_s.scans[k].ranges
            )
        # each pipelined message keeps its OWN revolution's stamp, so its
        # stamp-to-publish age runs ~one revolution period older than the
        # synchronous path's (this is the declared staleness; a regression
        # stamping with the publish-time revolution would erase the gap)
        period = pub_p.scans[0].scan_time  # dummy: 1/50 s
        age_p = np.median([
            pub_p.pub_times[k] - pub_p.scans[k].stamp for k in range(n)
        ])
        age_s = np.median([
            pub_s.pub_times[k] - pub_s.scans[k].stamp for k in range(n)
        ])
        assert age_p - age_s > 0.5 * period, (age_p, age_s, period)

    def test_pipeline_drained_when_toggled_off_midstream(self):
        """Flipping pipelined_publish off mid-stream must drain the
        in-flight revolution immediately and in order — not hold it until
        the next FSM transition and publish it arbitrarily late (advisor
        round-3 finding).  Discriminator: the toggled run's message
        sequence stays gap-free and identical to an all-synchronous run's
        (the dummy's phase is deterministic per revolution)."""
        chain_kw = dict(
            dummy_mode=True,
            filter_backend="cpu",
            filter_chain=("clip", "median", "voxel"),
            filter_window=4,
            voxel_grid_size=32,
        )

        def run(params, toggle_off_at=None):
            pub = CollectingPublisher()
            node = RPlidarNode(
                params, pub,
                driver_factory=lambda: DummyLidarDriver(scan_rate_hz=50.0),
                fsm_timings=FsmTimings.fast(),
            )
            launch(node)
            if toggle_off_at is not None:
                assert _wait(lambda: pub.scan_count >= toggle_off_at)
                params.pipelined_publish = False
            assert _wait(lambda: pub.scan_count >= 8)
            node.deactivate()
            node.shutdown()
            return pub

        pub_t = run(
            DriverParams(pipelined_publish=True, **chain_kw), toggle_off_at=3
        )
        pub_s = run(DriverParams(**chain_kw))
        n = min(pub_t.scan_count, pub_s.scan_count)
        assert n >= 8
        for k in range(n):
            np.testing.assert_array_equal(
                pub_t.scans[k].ranges, pub_s.scans[k].ranges
            )


class FlakyDriver(DummyLidarDriver):
    """Fault-injecting fake: healthy scans, then grab failures, then
    recovery after the FSM recreates the driver."""

    fail_after = 3
    instances = 0

    def __init__(self):
        super().__init__(scan_rate_hz=500.0)
        FlakyDriver.instances += 1
        self.generation = FlakyDriver.instances
        self.grabs = 0

    def grab_scan_data(self, timeout_s=2.0):
        self.grabs += 1
        if self.generation == 1 and self.grabs > self.fail_after:
            return None  # simulate unplugged device
        return super().grab_scan_data(timeout_s)


class DeadDriver(DummyLidarDriver):
    """Never connects — exercises the CONNECTING retry loop."""

    def __init__(self):
        super().__init__(scan_rate_hz=500.0)
        self.attempts = 0

    def connect(self, *a):
        self.attempts += 1
        return False

    def is_connected(self):
        return False


class SickDriver(DummyLidarDriver):
    """Health ERROR until the third check — exercises the health gate."""

    checks = 0

    def get_health(self):
        SickDriver.checks += 1
        return DeviceHealth.ERROR if SickDriver.checks < 3 else DeviceHealth.OK


class RaisingDriver(DummyLidarDriver):
    """Throws from grab — the FSM loop must route it through RESETTING
    instead of dying (the reference loop survives all hardware faults)."""

    instances = 0

    def __init__(self):
        super().__init__(scan_rate_hz=500.0)
        RaisingDriver.instances += 1
        self.generation = RaisingDriver.instances
        self.grabs = 0

    def grab_scan_data(self, timeout_s=2.0):
        self.grabs += 1
        if self.generation == 1 and self.grabs > 2:
            raise OSError("device vanished mid-read")
        return super().grab_scan_data(timeout_s)


class TestFaultRecovery:
    def test_pipelined_pending_drained_at_reset_not_after_recovery(self):
        """With pipelined_publish on, the revolution in flight when the
        device dies must be published as the FSM LEAVES RUNNING — not
        held across the recovery backoff and published (stale by the
        whole gap) into the resumed stream.  Discriminator: no message's
        publish time may trail its own revolution by anywhere near the
        reset backoff (an undrained pending would trail by at least
        backoff + reconnect)."""
        import time as _time

        class TimestampingPublisher(CollectingPublisher):
            def __init__(self):
                super().__init__()
                self.pub_times = []

            def publish_scan(self, msg):
                super().publish_scan(msg)
                self.pub_times.append(_time.monotonic())

        FlakyDriver.instances = 0
        backoff = 0.4
        params = DriverParams(
            dummy_mode=True,
            max_retries=2,
            filter_backend="cpu",
            filter_chain=("clip", "median", "voxel"),
            filter_window=4,
            voxel_grid_size=32,
            pipelined_publish=True,
        )
        timings = FsmTimings.fast()
        timings = type(timings)(**{
            **{f: getattr(timings, f) for f in timings.__dataclass_fields__},
            "reset_backoff_s": backoff,
        })
        pub = TimestampingPublisher()
        node = RPlidarNode(
            params, pub,
            driver_factory=FlakyDriver,
            fsm_timings=timings,
        )
        launch(node)
        assert _wait(lambda: node.fsm.reset_count >= 1)
        before = pub.scan_count
        assert _wait(lambda: pub.scan_count > before + 2)
        node.shutdown()
        # stamps strictly increase through the reset...
        stamps = [pub.scans[k].stamp for k in range(pub.scan_count)]
        assert all(b > a for a, b in zip(stamps, stamps[1:])), stamps
        # ...and every publish happened promptly relative to its own
        # revolution — nothing crossed the recovery backoff undrained
        ages = [
            pub.pub_times[k] - pub.scans[k].stamp
            for k in range(pub.scan_count)
        ]
        assert max(ages) < 0.5 * backoff, max(ages)

    def test_raising_driver_recovers_via_reset(self):
        RaisingDriver.instances = 0
        node, pub = make_node(factory=RaisingDriver)
        launch(node)
        assert _wait(lambda: node.fsm.reset_count >= 1)
        before = pub.scan_count
        assert _wait(lambda: pub.scan_count > before + 2)
        assert RaisingDriver.instances >= 2
        assert node.fsm._thread.is_alive()
        node.shutdown()

    def test_grab_failures_trigger_reset_and_recovery(self):
        FlakyDriver.instances = 0
        params = DriverParams(dummy_mode=True, max_retries=2)
        node, pub = make_node(params, factory=FlakyDriver)
        launch(node)
        # first generation fails after 3 grabs -> RESETTING -> new driver scans
        assert _wait(lambda: node.fsm.reset_count >= 1)
        before = pub.scan_count
        assert _wait(lambda: pub.scan_count > before + 2)
        assert FlakyDriver.instances >= 2
        node.shutdown()

    def test_connect_retry_loop(self):
        node, pub = make_node(factory=DeadDriver)
        launch(node)
        assert _wait(lambda: node.fsm.driver is not None and node.fsm.driver.attempts >= 3)
        assert node.fsm.state is DriverState.CONNECTING
        assert pub.scan_count == 0
        node.shutdown()

    def test_health_gate_blocks_then_passes(self):
        SickDriver.checks = 0
        node, pub = make_node(factory=SickDriver)
        launch(node)
        assert _wait(lambda: pub.scan_count >= 1)
        assert SickDriver.checks >= 3
        node.shutdown()


class TestDynamicReconfigure:
    def test_rejected_when_not_ready(self):
        node, _ = make_node()
        node.configure()  # not activated: no driver yet
        ok, reason = node.set_parameters({"rpm": 700})
        assert not ok
        assert "not ready" in reason.lower()

    def test_rpm_update_and_validation(self):
        node, pub = make_node()
        launch(node)
        assert _wait(lambda: node.fsm.state is DriverState.RUNNING)
        ok, _ = node.set_parameters({"rpm": 700})
        assert ok
        assert node.params.rpm == 700
        ok, reason = node.set_parameters({"rpm": 1300})
        assert not ok and "range" in reason
        ok, _ = node.set_parameters({"scan_processing": True})
        assert ok and node.params.scan_processing
        node.shutdown()

    def test_unknown_parameter_rejected(self):
        node, _ = make_node()
        launch(node)
        assert _wait(lambda: node.fsm.state is DriverState.RUNNING)
        ok, reason = node.set_parameters({"frame_id": "x"})
        assert not ok and "not runtime-mutable" in reason
        node.shutdown()


class TestDiagnostics:
    def test_states_reported(self):
        node, pub = make_node()
        node.configure()
        assert pub.diagnostics[-1].message == "Node Inactive (Lifecycle)"
        launch(node)
        assert _wait(lambda: node.fsm.state is DriverState.RUNNING)
        node._update_diagnostics()
        assert pub.diagnostics[-1].message == "Scanning"
        assert pub.diagnostics[-1].hardware_id.startswith("rplidar-")
        node.shutdown()

    def test_kv_details_surface(self):
        """REP-107 detail parity (src/rplidar_node.cpp:521-544): port,
        target RPM, device info, plus the per-stage p99 latencies this
        framework adds once scans have flowed."""
        node, pub = make_node()
        launch(node)
        assert _wait(lambda: pub.scan_count >= 2)
        node._update_diagnostics()
        values = pub.diagnostics[-1].values
        for key in ("Serial Port", "Target RPM", "Device Info",
                    "FSM State", "Lifecycle"):
            assert key in values, values
        assert values["FSM State"] == DriverState.RUNNING.value
        assert any(k.startswith("p99 ") for k in values), values
        node.shutdown()
