"""Bit-exact parity suite for the Pallas de-skew kernels
(ops/pallas_deskew.py vs the XLA arms vs the NumPy twins).

The contract under test is EQUALITY, not closeness: the de-skew
datapath is int32 end to end (min / sum / compare — evaluation-order
independent), so the VMEM-tiled kernels (interpret mode on this CPU
backend — the exact code path a pallas-pinned CPU config runs) must
reproduce ops/deskew's jnp arms and ops/deskew_ref.py byte-for-byte:
beam-min profiles, rasterized sub-sweeps, shift-search scores and the
full motion estimates — across beam geometries, degenerate inputs,
score ties, and the fused ingest program itself (vmapped fleet +
``lax.scan`` super-tick lowerings with ``deskew_backend='pallas'``).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.ops.deskew import (
    RECON_EMPTY,
    DeskewConfig,
    estimate_motion,
    profile_from_nodes,
    rasterize_subsweep,
    resolve_deskew_backend,
    shift_candidates,
)
from rplidar_ros2_driver_tpu.ops.deskew_ref import (
    estimate_motion_np,
    profile_from_nodes_np,
    rasterize_subsweep_np,
)

pytestmark = pytest.mark.pallas

BEAMS = 256


def _cfg(backend, **over):
    base = dict(
        recon_beams=BEAMS, profile_beams=64, shift_window=4,
        recon_window=3, backend=backend,
    )
    base.update(over)
    return DeskewConfig(**base)


def _rand_nodes(rng, n=600):
    angle = rng.integers(0, 65536, n).astype(np.int32)
    dist = rng.integers(0, 0x3FFFF, n).astype(np.int32)
    dist[rng.random(n) < 0.1] = 0
    quality = rng.integers(0, 256, n).astype(np.int32)
    valid = rng.random(n) < 0.9
    return angle, dist, quality, valid


@pytest.mark.parametrize(
    "beams,prof", [(256, 64), (2048, 256), (100, 128), (8, 1024)]
)
def test_kernel_parity_random(beams, prof):
    """beam-min (profile + rasterizer) and the full motion estimate:
    pallas == xla == numpy, byte-for-byte, across beam geometries
    including a non-lane-multiple recon grid and the widest profile."""
    rng = np.random.default_rng(beams + prof)
    cx = _cfg("xla", recon_beams=beams, profile_beams=prof,
              shift_window=min(8, prof // 8))
    cp = dataclasses.replace(cx, backend="pallas")
    for _ in range(3):
        angle, dist, quality, valid = _rand_nodes(rng)
        rx = np.asarray(rasterize_subsweep(angle, dist, quality, valid, cx))
        rp = np.asarray(rasterize_subsweep(angle, dist, quality, valid, cp))
        rn = rasterize_subsweep_np(angle, dist, quality, valid, cx)
        np.testing.assert_array_equal(rx, rp)
        np.testing.assert_array_equal(rx, rn)

        px = np.asarray(profile_from_nodes(angle, dist, valid, cx))
        pp = np.asarray(profile_from_nodes(angle, dist, valid, cp))
        pn = profile_from_nodes_np(angle, dist, valid, cx)
        np.testing.assert_array_equal(px, pp)
        np.testing.assert_array_equal(px, pn)

        a2, d2, _q2, v2 = _rand_nodes(rng)
        p2 = profile_from_nodes_np(a2, d2, v2, cx)
        mx = np.asarray(estimate_motion(pn, p2, cx))
        mp = np.asarray(estimate_motion(pn, p2, cp))
        mn = estimate_motion_np(pn, p2, cx)
        np.testing.assert_array_equal(mx, mp)
        np.testing.assert_array_equal(mx, mn)


def test_degenerate_inputs():
    """All-invalid, empty-overlap and single-node inputs: the pallas
    arm inherits the exact degradation contract (EMPTY profile, exact
    zero motion — identity, never garbage)."""
    cx, cp = _cfg("xla"), _cfg("pallas")
    n = 64
    angle = np.linspace(0, 65535, n).astype(np.int32)
    dist = np.full(n, 4000, np.int32)
    q = np.full(n, 100, np.int32)
    none = np.zeros(n, bool)
    for cfg in (cx, cp):
        prof = np.asarray(profile_from_nodes(angle, dist, none, cfg))
        assert (prof == RECON_EMPTY).all()
        seg = np.asarray(rasterize_subsweep(angle, dist, q, none, cfg))
        assert (seg == RECON_EMPTY).all()
    one = none.copy()
    one[5] = True
    np.testing.assert_array_equal(
        np.asarray(profile_from_nodes(angle, dist, one, cx)),
        np.asarray(profile_from_nodes(angle, dist, one, cp)),
    )
    empty = np.full(cx.profile_beams, RECON_EMPTY, np.int32)
    for cfg in (cx, cp):
        m = np.asarray(estimate_motion(empty, empty, cfg))
        np.testing.assert_array_equal(m, np.zeros(3, np.int32))


def test_featureless_tie_prefers_identity():
    """A featureless scene scores every shift equally; the |s|-ordered
    first-min-wins argmin must land the identity on BOTH backends (the
    candidate plane is built in shared code precisely so tiling cannot
    flip a tie)."""
    flat = np.full(64, 3000, np.int32)
    for backend in ("xla", "pallas"):
        m = np.asarray(estimate_motion(flat, flat, _cfg(backend)))
        np.testing.assert_array_equal(m, np.zeros(3, np.int32))


def test_shift_candidate_order_shared():
    """The pallas shift search consumes the SAME |s|-ordered candidate
    table as the XLA arm (shared shift_candidates) — a real rotation
    must land the same candidate on both."""
    cfg_x, cfg_p = _cfg("xla"), _cfg("pallas")
    cands = shift_candidates(cfg_x)
    assert cands[0] == 0
    rng = np.random.default_rng(11)
    prof0 = rng.integers(500, 5000, 64).astype(np.int32)
    for s in (-3, -1, 1, 3):
        rolled = np.roll(prof0, s)
        mx = np.asarray(estimate_motion(prof0, rolled, cfg_x))
        mp = np.asarray(estimate_motion(prof0, rolled, cfg_p))
        np.testing.assert_array_equal(mx, mp)


def test_resolver():
    assert resolve_deskew_backend("auto") == "xla"
    assert resolve_deskew_backend("pallas") == "pallas"
    assert resolve_deskew_backend("xla", "tpu") == "xla"
    with pytest.raises(ValueError, match="backend"):
        DeskewConfig(recon_beams=BEAMS, backend="mosaic")


def test_fused_program_parity_pallas_backend():
    """The whole fused ingest program with ``deskew_backend='pallas'``
    (kernels inside the vmapped fleet + scanned super-tick lowerings,
    interpret mode here): reconstructed sweeps, revolution outputs and
    motion metas byte-equal to the xla-backend program."""
    from tests.test_fused_mapping import _build, _byte_ticks, _dense_frames

    streams = 2
    ticks = _byte_ticks(_dense_frames(3), streams)

    def run(dbk):
        svc = _build(
            "fused", streams, super_tick_max=2, deskew_backend=dbk
        )
        svc.fleet_ingest.recon_log = True
        outs = []
        for t in ticks:
            res = svc.submit_bytes(t)
            outs.append([
                None if r is None else np.asarray(r.ranges).copy()
                for r in res
            ])
        return svc, outs

    sx, ox = run("xla")
    sp, op = run("pallas")
    for a_row, b_row in zip(ox, op):
        for a, b in zip(a_row, b_row):
            assert (a is None) == (b is None)
            if a is not None:
                np.testing.assert_array_equal(a, b)
    for i in range(streams):
        hx = sx.fleet_ingest.recon_history[i]
        hp = sp.fleet_ingest.recon_history[i]
        assert len(hx) == len(hp) and len(hx) > 0
        for (plx, _px), (plp, _pp) in zip(hx, hp):
            np.testing.assert_array_equal(plx, plp)
    for k in ("log_odds", "pose", "origin_xy", "revision"):
        np.testing.assert_array_equal(
            np.asarray(sx.mapper.snapshot()[k]),
            np.asarray(sp.mapper.snapshot()[k]),
        )
