"""Shard-loss failover: elastic fleet membership, cross-shard stream
migration, and crash-safe re-admission (parallel/service.
ElasticFleetService + parallel/sharding.FleetTopology +
driver/health.ShardHealth + driver/chaos.ShardChaosSchedule).

The acceptance contract this suite pins:

  * **Kill -> evacuate -> re-admit, bit-exact** — a deterministic chaos
    shard-kill of 1 of 4 shards (8 streams) completes the full cycle:
    every victim stream's filter+map state is restored from its last
    per-stream snapshot into a surviving shard's idle lane BEFORE bytes
    flow (decode carries reset), and on re-admission streams migrate
    back via fresh live snapshots.  Every stream's outputs and final
    map are byte-for-byte equal to the host-golden replay of its
    recorded plan (feed the included ticks, reset decoder+assembler at
    each recorded reset — the filter window and map carry through).
  * **Zero recompiles / zero implicit transfers** — the whole cycle
    runs inside utils/guards.steady_state: membership changes relabel
    which lanes are live (the idle padding lanes the compiled programs
    already encode), never shapes, and every migration rides the
    row-sized dynamic-index gather/scatter programs warmed at
    precompile.
  * The placement planner, shard FSM, and shard-loss schedule as
    units; the /diagnostics shard-topology rendering; the snapshot
    version-mismatch reject paths the migration depends on.
"""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.driver.assembly import ScanAssembler
from rplidar_ros2_driver_tpu.driver.chaos import (
    ShardChaosConfig,
    ShardChaosSchedule,
)
from rplidar_ros2_driver_tpu.driver.decode import BatchScanDecoder
from rplidar_ros2_driver_tpu.driver.health import (
    ShardHealth,
    ShardHealthConfig,
    ShardState,
)
from rplidar_ros2_driver_tpu.driver.ingest import (
    INGEST_STREAM_SNAPSHOT_VERSION,
    FleetFusedIngest,
)
from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain
from rplidar_ros2_driver_tpu.mapping.mapper import FleetMapper
from rplidar_ros2_driver_tpu.ops.scan_match import MAP_STATE_VERSION
from rplidar_ros2_driver_tpu.parallel.service import ElasticFleetService
from rplidar_ros2_driver_tpu.parallel.sharding import FleetTopology
from rplidar_ros2_driver_tpu.utils import guards

from test_chaos import DENSE, OUT_FIELDS, _fleet_ticks, _map_params
from test_fused_ingest import BEAMS, _params

MAP_KEYS = ("log_odds", "pose", "origin_xy", "revision")


def _host_replay_plan(ticks, plan, streams, params):
    """The host-golden replay of an elastic pod's recorded per-stream
    plan (ElasticFleetService.replay_plan): per stream, an independent
    decoder + assembler + chain + host mapper over every tick EXCEPT the
    ``excluded`` ones (ticks whose effect died with a shard), with the
    decoder and assembler reset at each ``resets`` tick (the migration's
    decode-carry reset) — the filter window and map, like the restored
    snapshot rows, carry straight through."""
    per_tick = [[None] * streams for _ in ticks]
    mappers = [FleetMapper(params, 1, beams=BEAMS) for _ in range(streams)]
    for i in range(streams):
        completed: list = []
        asm = ScanAssembler(
            on_complete=lambda sc, c=completed: c.append(dict(sc))
        )
        dec = BatchScanDecoder(asm)
        chain = ScanFilterChain(params, beams=BEAMS, warmup=False)
        resets = set(plan[i]["resets"])
        excluded = set(plan[i]["excluded"])
        for t, tick in enumerate(ticks):
            if t in resets:
                dec.reset()
                asm.reset()
            if t in excluded:
                continue
            item = tick[i]
            n0 = len(completed)
            if item:
                dec.on_measurement_batch(item[0], list(item[1]))
            outs = [
                chain.process_raw(
                    sc["angle_q14"], sc["dist_q2"], sc["quality"], sc["flag"]
                )
                for sc in completed[n0:]
            ]
            if outs:
                per_tick[t][i] = outs[-1]
                mappers[i].submit([outs[-1]])
    return per_tick, mappers


def _pod_params(**over):
    base = dict(
        fleet_ingest_backend="fused", map_backend="fused",
        shard_count=4, shard_lanes=0,
        failover_snapshot_ticks=4,
        shard_backoff_base_s=0.45, shard_backoff_max_s=2.0,
        shard_backoff_jitter=0.0,
        shard_starvation_ticks=8, shard_suspect_ticks=4,
        shard_probation_ticks=2,
    )
    base.update(over)
    return _map_params(**base)


# ---------------------------------------------------------------------------
# the tier-1 acceptance test
# ---------------------------------------------------------------------------


class TestShardFailoverParity:
    def test_shard_kill_evacuate_readmit_bit_exact_zero_recompiles(self):
        """4 shards x 8 streams; a deterministic chaos kill takes shard
        1 (streams 1 and 5) down for 2 ticks past its last snapshot.
        The pod must evacuate both victims onto surviving shards' idle
        lanes from their stored snapshots, re-admit the shard after
        backoff+probe and migrate streams back via fresh live
        snapshots — all with zero recompiles / zero implicit transfers,
        and every stream's outputs + final map byte-for-byte equal to
        the host-golden replay of the recorded plan."""
        streams, shards, revs = 8, 4, 12
        ticks = _fleet_ticks(streams, revs)  # 2 ticks per revolution
        kill_start, kill_stop = 10, 12  # last snapshot at tick 7
        params = _pod_params()
        fake = {"now": 0.0}
        pod = ElasticFleetService(
            params, streams, shards=shards, beams=BEAMS,
            fleet_ingest_buckets=(8,), clock=lambda: fake["now"],
        )
        assert pod.topology.lanes == 3  # auto: ceil(8 / (4-1))
        pod.attach_shard_chaos(ShardChaosSchedule(ShardChaosConfig(
            kills=((1, kill_start, kill_stop),),
        )))
        pod.precompile([DENSE])

        outs_log = []
        warm = 3  # compiles + window fill, outside the guarded region
        for tick in ticks[:warm]:
            outs_log.append(pod.submit_bytes(tick))
            fake["now"] += 0.1
        with guards.steady_state(tag="shard kill/evacuate/readmit"):
            for tick in ticks[warm:]:
                outs_log.append(pod.submit_bytes(tick))
                fake["now"] += 0.1

        # the cycle completed: one loss, one evacuation, one
        # re-admission, and the victims migrated twice (out and back)
        kinds = [e[1] for e in pod.events]
        assert "lost" in kinds and "evacuated" in kinds
        assert "readmitting" in kinds and "migrated" in kinds
        assert pod.evacuations == 1 and pod.readmits == 1
        assert pod.migrations == 4  # 2 victims out + 2 back
        assert pod.shard_health[1].state is ShardState.UP  # probation done
        assert pod.topology.unhosted() == []
        victims = {s for (_t, kind, s, *_r) in pod.events
                   if kind == "evacuated"}
        assert victims == {1, 5}  # round-robin: streams 1,5 on shard 1

        # the recorded replay plan: the victims lost exactly the ticks
        # the dead shard absorbed after their last snapshot, and reset
        # decode carries at the evacuation and at the migration back
        plan = pod.replay_plan()
        readmit_tick = next(
            t for (t, kind, *_r) in pod.events if kind == "readmitting"
        )
        for i in range(streams):
            if i in victims:
                assert plan[i]["excluded"] == [8, 9], i
                assert plan[i]["resets"] == [kill_start, readmit_tick], i
            else:
                assert plan[i]["excluded"] == [] and plan[i]["resets"] == []

        # host-golden replay: outputs bit-exact at every non-excluded
        # tick, for survivors and migrated victims alike
        host_params = _pod_params(map_backend="host")
        per_tick, host_mappers = _host_replay_plan(
            ticks, plan, streams, host_params
        )
        published = 0
        post_migration = {i: 0 for i in victims}
        for t, row in enumerate(outs_log):
            for i in range(streams):
                if t in set(plan[i]["excluded"]):
                    continue  # the tick's effect died with the shard
                h, f = per_tick[t][i], row[i]
                assert (h is None) == (f is None), (t, i)
                if h is None:
                    continue
                published += 1
                if i in victims and t >= readmit_tick:
                    post_migration[i] += 1
                for field in OUT_FIELDS:
                    assert np.array_equal(
                        np.asarray(getattr(h, field)),
                        np.asarray(getattr(f, field)),
                    ), (t, i, field)
        assert published >= 2 * streams  # real coverage, not idle ticks
        # every migrated stream published bit-exact output AFTER its
        # migration back — the "post-migration output" criterion
        assert all(v >= 1 for v in post_migration.values())

        # final maps: each stream's fused map row (pulled from whichever
        # shard hosts it now) is bit-exact vs its host mapper — the
        # victims' maps crossed TWO snapshot/restore migrations
        for i in range(streams):
            s, lane = pod.topology.placement(i)
            fused_row = pod.shards[s].mapper.snapshot_stream(lane)
            host_row = host_mappers[i].snapshot_stream(0)
            for k in MAP_KEYS:
                assert np.array_equal(fused_row[k], host_row[k]), (i, k)

        # the evacuation-latency decomposition the bench also reports
        ev = pod.last_evacuation
        assert ev["shard"] == 1 and sorted(ev["streams"]) == [1, 5]
        assert ev["snapshot_pull_ms"] >= 0.0
        assert ev["restore_scatter_ms"] > 0.0
        assert ev["first_tick_ms"] > 0.0  # the tick that resumed flow

    def test_heartbeat_failure_evacuates_and_excludes_the_tick(self):
        """A raised dispatch is a shard heartbeat failure: the shard is
        LOST mid-tick, its victims lose THAT tick's bytes (consumed by
        the dead dispatch — recorded in the replay plan) and are
        restored onto survivors before the next tick's bytes flow."""
        streams, shards = 4, 2
        ticks = _fleet_ticks(streams, 8)
        params = _pod_params(shard_count=2, map_enable=False)
        fake = {"now": 0.0}
        pod = ElasticFleetService(
            params, streams, shards=shards, beams=BEAMS,
            fleet_ingest_buckets=(8,), clock=lambda: fake["now"],
        )
        pod.precompile([DENSE])
        boom_tick = 4
        real_submit = pod.shards[1].submit_bytes

        def maybe_boom(items):
            if pod.tick_no == boom_tick:
                raise RuntimeError("device fell off the bus")
            return real_submit(items)

        pod.shards[1].submit_bytes = maybe_boom
        outs_log = []
        for tick in ticks:
            outs_log.append(pod.submit_bytes(tick))
            fake["now"] += 0.1
        assert pod.evacuations == 1
        assert pod.shard_health[1].losses == 1
        lost = next(e for e in pod.events if e[1] == "lost")
        assert lost[2] == 1 and "heartbeat" in lost[3]
        plan = pod.replay_plan()
        for i in (1, 3):  # round-robin: shard 1 hosted streams 1, 3
            assert boom_tick in plan[i]["excluded"], i
            assert boom_tick in plan[i]["resets"], i
        # the victims kept publishing from their new lanes, bit-exact
        per_tick, _ = _host_replay_plan(
            ticks, plan, streams, _pod_params(shard_count=2,
                                              map_enable=False),
        )
        resumed = 0
        for t in range(boom_tick + 1, len(ticks)):
            for i in (1, 3):
                h, f = per_tick[t][i], outs_log[t][i]
                assert (h is None) == (f is None), (t, i)
                if h is not None:
                    resumed += 1
                    assert np.array_equal(
                        np.asarray(h.ranges), np.asarray(f.ranges)
                    ), (t, i)
        assert resumed >= 2

    def test_starvation_loss_evacuates_via_the_fsm(self):
        """An FSM-driven loss (no exception, no chaos kill): the
        victims' upstream goes silent, tick starvation walks the shard
        UP -> SUSPECT -> LOST inside the tick loop, and the SAME
        wipe+evacuate handler as a hard kill must run — victims
        restored onto survivors, replay plan recorded, shard
        re-admitted once bytes resume, everything bit-exact."""
        streams, shards = 4, 2
        ticks = _fleet_ticks(streams, 14)
        # silence ends BEFORE the re-admission poll: a shard whose
        # upstream is still dry at probation relapses (escalated) by
        # design, which would add a second loss/evacuation cycle here
        silent_start, silent_stop = 6, 12
        params = _pod_params(
            shard_count=2, map_enable=False,
            shard_starvation_ticks=2, shard_suspect_ticks=2,
        )
        fake = {"now": 0.0}
        pod = ElasticFleetService(
            params, streams, shards=shards, beams=BEAMS,
            fleet_ingest_buckets=(8,), clock=lambda: fake["now"],
        )
        pod.precompile([DENSE])
        victims = (1, 3)  # round-robin: shard 1's streams
        fed = []
        outs_log = []
        for t, tick in enumerate(ticks):
            tick = list(tick)
            if silent_start <= t < silent_stop:
                for i in victims:
                    tick[i] = None  # upstream dried up
            fed.append(tick)
            outs_log.append(pod.submit_bytes(tick))
            fake["now"] += 0.1
        lost = next(e for e in pod.events if e[1] == "lost")
        assert lost[2] == 1 and "starved" in lost[3]
        assert pod.evacuations == 1 and pod.readmits == 1
        from rplidar_ros2_driver_tpu.driver.health import ShardState

        assert pod.shard_health[1].state is ShardState.UP
        plan = pod.replay_plan()
        for i in victims:
            # the t=7 refresh fell inside the SUSPECT window (silence
            # began at 6, starvation_ticks=2) and was therefore
            # SKIPPED: the FSM had stopped trusting the shard's state,
            # so the last trusted snapshot is t=3 and the victims'
            # data ticks 4 and 5 died with the distrusted device state
            assert plan[i]["excluded"] == [4, 5], i
            assert len(plan[i]["resets"]) == 2, i  # out and back
        per_tick, _ = _host_replay_plan(
            fed, plan, streams, _pod_params(shard_count=2,
                                            map_enable=False),
        )
        resumed = 0
        for t, row in enumerate(outs_log):
            for i in range(streams):
                if t in set(plan[i]["excluded"]):
                    continue
                h, f = per_tick[t][i], row[i]
                assert (h is None) == (f is None), (t, i)
                if h is not None:
                    assert np.array_equal(
                        np.asarray(h.ranges), np.asarray(f.ranges)
                    ), (t, i)
                    if i in victims and t >= silent_stop:
                        resumed += 1
        assert resumed >= 2  # victims published again, bit-exact

    def test_double_loss_unhosted_victims_replay_stays_bit_exact(self):
        """Double loss beyond capacity: the second shard's victims find
        no idle lane and go unhosted — the ticks the dead shard
        absorbed after their last snapshot must STILL be excluded from
        the replay plan (their later re-hosting restores from that
        snapshot), and once the first shard re-admits they come back
        bit-exact."""
        streams, shards = 6, 3
        ticks = _fleet_ticks(streams, 14)
        params = _pod_params(shard_count=3, map_enable=False)
        fake = {"now": 0.0}
        pod = ElasticFleetService(
            params, streams, shards=shards, beams=BEAMS,
            fleet_ingest_buckets=(8,), clock=lambda: fake["now"],
        )
        assert pod.topology.lanes == 3
        # shard 0 recovers; shard 1 never does
        pod.attach_shard_chaos(ShardChaosSchedule(ShardChaosConfig(
            kills=((0, 6, 12), (1, 9, 0)),
        )))
        pod.precompile([DENSE])
        outs_log = []
        for tick in ticks:
            outs_log.append(pod.submit_bytes(tick))
            fake["now"] += 0.1
        assert pod.evacuations == 2 and pod.readmits == 1
        assert pod.topology.unhosted() == []
        # shard 1's victims at its death: its own streams plus the
        # shard-0 evacuee it absorbed — all went unhosted
        stranded = {1, 4, 0}
        readmit_tick = next(
            t for (t, kind, *_r) in pod.events if kind == "readmitting"
        )
        plan = pod.replay_plan()
        for i in stranded:
            # tick 8 (after the t=7 snapshot, before the t=9 loss) died
            # with shard 1's state: it must be excluded even though the
            # stream found no lane to evacuate to
            assert 8 in plan[i]["excluded"], (i, plan[i])
            # and the whole unhosted stretch rides along
            assert set(range(9, readmit_tick)) <= set(
                plan[i]["excluded"]
            ), i
            assert readmit_tick in plan[i]["resets"], i
        per_tick, _ = _host_replay_plan(
            ticks, plan, streams, _pod_params(shard_count=3,
                                              map_enable=False),
        )
        rehosted = 0
        for t, row in enumerate(outs_log):
            for i in range(streams):
                if t in set(plan[i]["excluded"]):
                    continue
                h, f = per_tick[t][i], row[i]
                assert (h is None) == (f is None), (t, i)
                if h is not None:
                    assert np.array_equal(
                        np.asarray(h.ranges), np.asarray(f.ranges)
                    ), (t, i)
                    if i in stranded and t > readmit_tick:
                        rehosted += 1
        assert rehosted >= 3  # every stranded stream came back

    def test_snapshots_disabled_victims_restart_fresh(self):
        """failover_snapshot_ticks=0: no snapshot store, so a victim
        restores as a FRESH stream — every pre-loss tick is excluded
        from its replay plan (the honest contract: the state is gone)."""
        streams, shards = 4, 2
        ticks = _fleet_ticks(streams, 8)
        params = _pod_params(
            shard_count=2, map_enable=False, failover_snapshot_ticks=0,
        )
        fake = {"now": 0.0}
        pod = ElasticFleetService(
            params, streams, shards=shards, beams=BEAMS,
            fleet_ingest_buckets=(8,), clock=lambda: fake["now"],
        )
        pod.precompile([DENSE])
        kill = 5
        pod.attach_shard_chaos(ShardChaosSchedule(ShardChaosConfig(
            kills=((1, kill, 0),),  # never recovers
        )))
        outs_log = []
        for tick in ticks:
            outs_log.append(pod.submit_bytes(tick))
            fake["now"] += 0.1
        plan = pod.replay_plan()
        for i in (1, 3):
            # every data tick before the kill died with the shard state
            assert plan[i]["excluded"] == list(range(kill)), i
        per_tick, _ = _host_replay_plan(
            ticks, plan, streams,
            _pod_params(shard_count=2, map_enable=False),
        )
        for t in range(kill, len(ticks)):
            for i in (1, 3):
                h, f = per_tick[t][i], outs_log[t][i]
                assert (h is None) == (f is None), (t, i)
                if h is not None:
                    assert np.array_equal(
                        np.asarray(h.ranges), np.asarray(f.ranges)
                    ), (t, i)

    def test_suspect_shard_snapshots_are_not_refreshed(self):
        """SUSPECT is the FSM saying 'this device's state may be
        garbage': the periodic refresh must not overwrite a stream's
        last trusted snapshot with an in-window pull — a later
        evacuation would restore FROM the distrusted state, breaking
        the host-golden replay contract (which excludes every tick
        since the last TRUSTED snapshot).  Refresh resumes at UP."""
        streams, shards = 4, 2
        ticks = _fleet_ticks(streams, 10)  # 20 ticks
        params = _pod_params(
            shard_count=2, map_enable=False, failover_snapshot_ticks=2,
            shard_starvation_ticks=2, shard_suspect_ticks=50,
        )
        fake = {"now": 0.0}
        pod = ElasticFleetService(
            params, streams, shards=shards, beams=BEAMS,
            fleet_ingest_buckets=(8,), clock=lambda: fake["now"],
        )
        pod.precompile([DENSE])
        victims = (1, 3)  # round-robin: shard 1's streams
        # victims silent for t in [6, 14): starvation (starved > 2)
        # marks shard 1 SUSPECT at t=8; suspect_ticks=50 keeps it
        # there (never LOST) until bytes resume
        silent_start, silent_stop = 6, 14
        frozen = {}
        for t, tick in enumerate(ticks):
            tick = list(tick)
            if silent_start <= t < silent_stop:
                for i in victims:
                    tick[i] = None
            pod.submit_bytes(tick)
            fake["now"] += 0.1
            if t == 8:
                assert pod.shard_health[1].state is ShardState.SUSPECT
                frozen = {i: pod._snap[i][0] for i in range(streams)}
                # SUSPECT entered at t=7 (starved 3 > 2), BEFORE that
                # tick's refresh ran: the last trusted snapshot is t=5
                assert frozen[victims[0]] == 5
            if t == 13:
                # three refresh intervals (t=9,11,13) passed while
                # SUSPECT: the stored snapshots never advanced
                for i in victims:
                    assert pod._snap[i][0] == frozen[i], i
        # bytes resumed at t=14 -> probation promoted the shard back to
        # UP and the refresh caught the victims up
        assert pod.shard_health[1].state is ShardState.UP
        for i in victims:
            assert pod._snap[i][0] > frozen[i], i
        # the healthy shard's streams refreshed throughout
        for i in (0, 2):
            assert pod._snap[i][0] == len(ticks) - 1, i

    def test_same_tick_double_kill_never_evacuates_onto_a_casualty(self):
        """Two shards chaos-killed at the SAME tick: the tick's full
        down set is marked LOST before any evacuation runs, so the
        first casualty's victims are never restored onto the second
        (and then immediately re-evacuated) — every evacuation's
        destination is a genuine survivor and no victim is evacuated
        twice (no phantom migration counts, no double restore work)."""
        streams, shards = 8, 4
        ticks = _fleet_ticks(streams, 8)
        params = _pod_params(map_enable=False)
        fake = {"now": 0.0}
        pod = ElasticFleetService(
            params, streams, shards=shards, beams=BEAMS,
            fleet_ingest_buckets=(8,), clock=lambda: fake["now"],
        )
        pod.attach_shard_chaos(ShardChaosSchedule(ShardChaosConfig(
            kills=((1, 6, 0), (2, 6, 0)),  # same tick, never recover
        )))
        pod.precompile([DENSE])
        for tick in ticks:
            pod.submit_bytes(tick)
            fake["now"] += 0.1
        assert pod.evacuations == 2
        evac = [e for e in pod.events if e[1] == "evacuated"]
        # (t, "evacuated", stream, src, dst, lane): every destination
        # is a surviving shard, and nobody was moved twice
        assert evac and all(e[4] in (0, 3) for e in evac)
        moved = [e[2] for e in evac]
        assert len(moved) == len(set(moved))
        # capacity check: 4 victims, 2 survivor idle lanes -> exactly
        # 2 restored, 2 honestly unhosted (not silently double-placed)
        assert len(moved) == 2
        assert len(pod.topology.unhosted()) == 2
        assert pod.migrations == 2


# ---------------------------------------------------------------------------
# placement planner units
# ---------------------------------------------------------------------------


class TestFleetTopology:
    def test_round_robin_initial_placement(self):
        topo = FleetTopology(8, 4, 3)
        for i in range(8):
            assert topo.placement(i)[0] == i % 4
        assert topo.streams_on(0) == [0, 4]
        assert topo.unhosted() == []

    def test_capacity_invariant_rejected(self):
        with pytest.raises(ValueError, match="cannot host"):
            FleetTopology(9, 2, 4)
        with pytest.raises(ValueError, match="survive a"):
            FleetTopology(8, 4, 2)  # (4-1)*2 < 8: one loss strands
        FleetTopology(8, 4, 3)      # (4-1)*3 >= 8: fine
        FleetTopology(4, 1, 4)      # single shard: no failover headroom
        with pytest.raises(ValueError):
            FleetTopology(0, 2, 2)
        with pytest.raises(ValueError):
            FleetTopology(2, 0, 2)
        with pytest.raises(ValueError):
            FleetTopology(2, 2, 0)

    def test_lane_items_routes_and_inverts(self):
        topo = FleetTopology(5, 2, 5)
        items = [f"s{i}" for i in range(5)]
        lane_items = topo.lane_items(0, items)
        assert lane_items == ["s0", "s2", "s4", None, None]
        assert topo.lane_streams(0) == [0, 2, 4, None, None]

    def test_release_assign_and_avoid(self):
        topo = FleetTopology(4, 2, 4)
        topo.release(2)
        assert topo.placement(2) is None and topo.unhosted() == [2]
        with pytest.raises(ValueError):
            topo.assign(0)  # already hosted
        got = topo.assign(2, avoid=(0,))
        assert got[0] == 1
        topo.release(2)
        assert topo.assign(2, avoid=(0, 1)) is None  # nowhere to go

    def test_evacuate_moves_all_victims_to_least_loaded(self):
        topo = FleetTopology(8, 4, 3)
        plan = topo.evacuate(1)
        assert [p[0] for p in plan] == [1, 5]
        assert all(dst != 1 for (_s, dst, _l) in plan)
        assert topo.streams_on(1) == [] and topo.unhosted() == []
        loads = [len(topo.streams_on(s)) for s in range(4)]
        assert sorted(loads) == [0, 2, 3, 3]

    def test_double_loss_degrades_to_unhosted(self):
        topo = FleetTopology(6, 3, 3)
        topo.evacuate(0)
        # second loss: the dead shard 0 is off-limits, shard 2 is full —
        # the victims degrade to unhosted instead of raising (or worse,
        # landing on the earlier casualty's wiped lanes)
        plan = topo.evacuate(1, avoid=(0,))
        assert plan == []
        assert topo.unhosted() == [0, 1, 4]

    def test_rebalance_into_restores_headroom(self):
        topo = FleetTopology(8, 4, 3)
        topo.evacuate(1)
        moves = topo.rebalance_into(1)
        # movers come from the most-loaded shards with their source
        # lane recorded (the live-snapshot source)
        assert len(moves) == 2
        for stream, src, src_lane, dst, _lane in moves:
            assert dst == 1 and src != 1 and src_lane >= 0
        loads = [len(topo.streams_on(s)) for s in range(4)]
        assert max(loads) - min(loads) <= 1

    def test_rebalance_places_unhosted_first(self):
        topo = FleetTopology(6, 3, 3)
        topo.evacuate(0)
        topo.evacuate(1, avoid=(0,))  # strands 0, 1, 4
        moves = topo.rebalance_into(1)
        unhosted_moves = [m for m in moves if m[1] == -1]
        assert {m[0] for m in unhosted_moves} == {0, 1, 4}
        assert topo.unhosted() == []

    def test_status_shape(self):
        topo = FleetTopology(4, 2, 4)
        st = topo.status()
        assert st == [
            {"host": 0, "streams": [0, 2], "lanes": 4, "load": 2.0},
            {"host": 0, "streams": [1, 3], "lanes": 4, "load": 2.0},
        ]


class TestPodTopology:
    """The two-level (host, shard, lane) coordinates — ISSUE 17's
    placement layer.  Hosts are contiguous equal shard blocks; every
    preference key degrades to the single-level rules at hosts=1."""

    def test_host_partition_validated(self):
        with pytest.raises(ValueError):
            FleetTopology(6, 4, 3, hosts=0)
        with pytest.raises(ValueError):
            FleetTopology(6, 4, 3, hosts=3)  # 4 shards % 3 hosts
        FleetTopology(6, 4, 3, hosts=2)
        FleetTopology(6, 4, 3, hosts=4)

    def test_host_queries(self):
        topo = FleetTopology(6, 4, 3, hosts=2)
        assert [topo.host_of(s) for s in range(4)] == [0, 0, 1, 1]
        assert topo.shards_on_host(0) == [0, 1]
        assert topo.shards_on_host(1) == [2, 3]
        with pytest.raises(IndexError):
            topo.host_of(4)
        with pytest.raises(IndexError):
            topo.shards_on_host(2)

    def test_coordinate_is_the_placement_plus_host(self):
        topo = FleetTopology(6, 4, 3, hosts=2)
        # round-robin: stream 4 landed on shard 0's second lane
        assert topo.coordinate(4) == (0, 0, 1)
        assert topo.coordinate(2) == (1, 2, 0)
        topo.release(4)
        assert topo.coordinate(4) is None

    def test_host_load_sums_the_weighted_shards(self):
        topo = FleetTopology(6, 4, 3, hosts=2)
        assert topo.host_load(0) == 4.0  # shards 0,1: streams 0,4,1,5
        assert topo.host_load(1) == 2.0
        topo.set_weight(2, 5.0)
        assert topo.host_load(1) == 6.0

    def test_assign_picks_the_cold_host_first(self):
        topo = FleetTopology(6, 4, 3, hosts=2)
        topo.release(5)
        # host 0 carries 3 streams, host 1 two: the cold HOST wins
        # before any shard compare, then its lowest-index cold shard
        assert topo.assign(5) == (2, 1)

    def test_assign_prefer_host_pins_the_choice(self):
        topo = FleetTopology(6, 4, 3, hosts=2)
        topo.release(5)
        # host 0 is the HOTTER host; the preference still pins it and
        # the least-loaded shard within it takes the stream
        assert topo.assign(5, prefer_host=0) == (1, 1)

    def test_evacuate_prefers_same_host_siblings(self):
        topo = FleetTopology(6, 4, 3, hosts=2)
        plan = topo.evacuate(0)
        # victim 0 fits shard 0's host-0 sibling; victim 4 overflows
        # host 0 (shard 1 is full at 3 lanes) and only then crosses
        assert plan == [(0, 1, 2), (4, 2, 1)]
        assert topo.host_of(plan[0][1]) == 0
        assert topo.host_of(plan[1][1]) == 1

    def test_rebalance_pulls_same_host_sources_first(self):
        topo = FleetTopology(6, 4, 3, hosts=2)
        topo.evacuate(2)          # stream 2 takes refuge on shard 3
        plan = topo.rebalance_into(2)
        # the refugee returns from the SAME-HOST sibling even though
        # host 0's shards are just as loaded
        assert plan == [(2, 3, 1, 2, 0)]
        assert topo.coordinate(2) == (1, 2, 0)

    def test_single_host_is_byte_identical_to_flat(self):
        flat = FleetTopology(8, 4, 3)
        one = FleetTopology(8, 4, 3, hosts=1)
        assert flat.evacuate(1) == one.evacuate(1)
        assert flat.rebalance_into(1) == one.rebalance_into(1)
        for i in range(8):
            assert flat.placement(i) == one.placement(i)
            assert one.coordinate(i)[0] == 0


# ---------------------------------------------------------------------------
# shard health FSM units
# ---------------------------------------------------------------------------


def _shard_cfg(**over):
    base = dict(
        starvation_ticks=2, suspect_ticks=2, probation_ticks=2,
        backoff_base_s=1.0, backoff_max_s=8.0, backoff_jitter=0.0,
    )
    base.update(over)
    return ShardHealthConfig(**base)


class TestShardHealthFsm:
    def test_force_lost_is_immediate_and_idempotent(self):
        t = {"now": 0.0}
        h = ShardHealth(_shard_cfg(), 3, clock=lambda: t["now"])
        assert h.hosting
        tr = h.force_lost("chaos: killed")
        assert tr == (ShardState.UP, ShardState.LOST)
        assert not h.hosting and h.losses == 1
        assert h.force_lost("again") is None  # already lost
        assert h.losses == 1

    def test_starvation_walks_up_suspect_lost(self):
        h = ShardHealth(_shard_cfg(), clock=lambda: 0.0)
        h.observe(True, 2)  # streamed once
        walked = [h.observe(True, 0) for _ in range(8)]
        trs = [tr for tr in walked if tr]
        assert trs[0] == (ShardState.UP, ShardState.SUSPECT)
        assert trs[1] == (ShardState.SUSPECT, ShardState.LOST)
        assert "starved" in h.last_reason

    def test_suspect_clears_on_probation(self):
        h = ShardHealth(_shard_cfg(suspect_ticks=5), clock=lambda: 0.0)
        h.observe(True, 1)
        for _ in range(4):
            h.observe(True, 0)
        assert h.state is ShardState.SUSPECT
        trs = [h.observe(True, 1) for _ in range(3)]
        assert (ShardState.SUSPECT, ShardState.UP) in [t for t in trs if t]

    def test_idle_shard_is_not_sick(self):
        h = ShardHealth(_shard_cfg(starvation_ticks=1), clock=lambda: 0.0)
        for _ in range(10):
            assert h.observe(False, 0) is None  # never streamed: idle
        assert h.state is ShardState.UP

    def test_readmit_gated_on_backoff_and_probe(self):
        t = {"now": 0.0}
        probe_ok = {"v": False}
        h = ShardHealth(
            _shard_cfg(), clock=lambda: t["now"],
            probe=lambda: probe_ok["v"],
        )
        h.force_lost()
        assert h.poll_readmit() is None  # backoff not expired
        t["now"] = h.release_at + 0.1
        assert h.poll_readmit() is None  # probe failed
        assert h.probe_failures == 1 and h.backoff.attempt == 2
        probe_ok["v"] = True
        t["now"] = h.release_at + 0.1
        assert h.poll_readmit() == (ShardState.LOST, ShardState.READMITTING)
        # probation: clean ticks walk back to UP and reset the backoff
        assert h.observe(True, 1) is None
        assert h.observe(True, 1) == (ShardState.READMITTING, ShardState.UP)
        assert h.readmissions == 1 and h.backoff.attempt == 0

    def test_readmitting_relapse_escalates(self):
        t = {"now": 0.0}
        h = ShardHealth(_shard_cfg(starvation_ticks=1), clock=lambda: t["now"])
        h.observe(True, 1)
        h.force_lost()
        t["now"] = h.release_at + 0.1
        h.poll_readmit()
        assert h.state is ShardState.READMITTING
        trs = [h.observe(True, 0) for _ in range(3)]
        assert (ShardState.READMITTING, ShardState.LOST) in [
            tr for tr in trs if tr
        ]
        assert h.backoff.attempt >= 2  # escalated, not reset

    def test_readmitting_silence_never_promotes(self):
        """A probe-passing-but-dead shard must not fill probation with
        offered-but-dry ticks: the clean streak counts PRODUCTIVE ticks
        only (completions, or true idle), so silence walks starvation to
        a relapse with the backoff ESCALATED — never to UP with the
        backoff reset (the flap-forever bug: with probation_ticks <=
        starvation_ticks the relapse edge used to be unreachable)."""
        t = {"now": 0.0}
        h = ShardHealth(
            _shard_cfg(starvation_ticks=4, probation_ticks=2),
            clock=lambda: t["now"],
        )
        h.observe(True, 1)  # streamed once, then died
        h.force_lost()
        t["now"] = h.release_at + 0.1
        h.poll_readmit()
        assert h.state is ShardState.READMITTING
        # relapse horizon: one REFILL window (the migrate-back decode
        # reset) on top of the normal starvation window = 2*4 ticks
        trs = [h.observe(True, 0) for _ in range(10)]
        assert ShardState.UP not in [tr[1] for tr in trs if tr]
        assert h.state is ShardState.LOST      # relapsed via starvation
        assert h.backoff.attempt >= 2          # escalated, not reset
        # productive probation ticks DO promote (dry ticks in between
        # are neutral: they neither fill nor reset the streak)
        t["now"] = h.release_at + 0.1
        h.poll_readmit()
        seq = [(True, 1), (True, 0), (True, 1)]
        trs = [h.observe(o, c) for o, c in seq]
        assert (ShardState.READMITTING, ShardState.UP) in [
            tr for tr in trs if tr
        ]
        assert h.backoff.attempt == 0  # reset on a REAL readmission

    def test_lost_clears_streaming_history(self):
        """An empty re-admitted shard (rebalance had no stream to give
        it) must be idle, not sick: the loss wiped the engines, so the
        'has ever streamed' flag restarts with them — carrying it
        across the loss made such a shard starve on silence and flap
        LOST/READMITTING forever on healthy hardware."""
        t = {"now": 0.0}
        h = ShardHealth(_shard_cfg(), clock=lambda: t["now"])
        h.observe(True, 1)  # streamed, then died
        h.force_lost()
        t["now"] = h.release_at + 0.1
        h.poll_readmit()
        assert h.state is ShardState.READMITTING
        trs = [h.observe(False, 0) for _ in range(4)]  # hosting nothing
        assert (ShardState.READMITTING, ShardState.UP) in [
            tr for tr in trs if tr
        ]
        assert h.state is ShardState.UP

    def test_probe_exception_counts_as_failure(self):
        t = {"now": 0.0}
        h = ShardHealth(
            _shard_cfg(), clock=lambda: t["now"],
            probe=lambda: (_ for _ in ()).throw(RuntimeError("dead")),
        )
        h.force_lost()
        t["now"] = h.release_at + 0.1
        assert h.poll_readmit() is None and h.probe_failures == 1

    def test_status_dict(self):
        h = ShardHealth(_shard_cfg(), 2, clock=lambda: 0.0)
        st = h.status()
        assert st["state"] == "up" and st["losses"] == 0
        for k in ("readmissions", "probe_failures", "backoff_attempt",
                  "backoff_s", "reason"):
            assert k in st

    def test_config_validates_domain(self):
        with pytest.raises(ValueError):
            ShardHealthConfig(starvation_ticks=0)
        with pytest.raises(ValueError):
            ShardHealthConfig(suspect_ticks=0)
        with pytest.raises(ValueError):
            ShardHealthConfig(probation_ticks=0)
        with pytest.raises(ValueError):
            ShardHealthConfig(backoff_base_s=2.0, backoff_max_s=1.0)
        with pytest.raises(ValueError):
            ShardHealthConfig(backoff_jitter=1.5)

    def test_from_params_reads_shard_keys(self):
        cfg = ShardHealthConfig.from_params(_params(
            shard_starvation_ticks=3, shard_suspect_ticks=5,
            shard_probation_ticks=7, shard_backoff_base_s=0.25,
            shard_backoff_max_s=9.0, shard_backoff_jitter=0.5,
        ))
        assert cfg.starvation_ticks == 3 and cfg.suspect_ticks == 5
        assert cfg.probation_ticks == 7
        assert cfg.backoff_base_s == 0.25 and cfg.backoff_max_s == 9.0
        assert cfg.backoff_jitter == 0.5


# ---------------------------------------------------------------------------
# shard-loss schedule units
# ---------------------------------------------------------------------------


class TestShardChaosSchedule:
    def test_explicit_kills_window(self):
        s = ShardChaosSchedule(ShardChaosConfig(kills=((1, 5, 8),)))
        assert not any(s.down(1, t) for t in range(5))
        assert all(s.down(1, t) for t in range(5, 8))
        assert not any(s.down(1, t) for t in range(8, 12))
        assert not any(s.down(0, t) for t in range(12))

    def test_stop_zero_never_recovers(self):
        s = ShardChaosSchedule(ShardChaosConfig(kills=((2, 3, 0),)))
        assert not s.down(2, 2)
        assert all(s.down(2, t) for t in (3, 100, 10_000))

    def test_seeded_outages_are_deterministic(self):
        cfg = ShardChaosConfig(seed=7, kill_rate=0.05, outage_ticks=4)
        a, b = ShardChaosSchedule(cfg), ShardChaosSchedule(cfg)
        got = [(s, t) for s in range(4) for t in range(200)
               if a.down(s, t)]
        assert got == [(s, t) for s in range(4) for t in range(200)
                       if b.down(s, t)]
        assert got  # the rate actually fires at this seed
        other = ShardChaosSchedule(ShardChaosConfig(
            seed=8, kill_rate=0.05, outage_ticks=4,
        ))
        assert got != [(s, t) for s in range(4) for t in range(200)
                       if other.down(s, t)]

    def test_outage_spans_outage_ticks(self):
        cfg = ShardChaosConfig(seed=3, kill_rate=0.02, outage_ticks=5)
        s = ShardChaosSchedule(cfg)
        downs = [t for t in range(400) if s.down(0, t)]
        assert downs
        # every down tick belongs to a run of >= 1 started by a draw;
        # runs last at least until the starting draw ages out
        runs = np.split(np.asarray(downs),
                        np.where(np.diff(downs) > 1)[0] + 1)
        assert all(len(r) >= 1 for r in runs)
        assert max(len(r) for r in runs) >= 5  # a full outage span

    def test_down_shards_aggregates(self):
        s = ShardChaosSchedule(ShardChaosConfig(
            kills=((0, 1, 3), (2, 2, 4)),
        ))
        assert s.down_shards(2, 4) == frozenset({0, 2})

    def test_invalid_config_rejected(self):
        with pytest.raises(ValueError):
            ShardChaosConfig(kill_rate=1.5)
        with pytest.raises(ValueError):
            ShardChaosConfig(kill_rate=0.1)  # needs outage_ticks
        with pytest.raises(ValueError):
            ShardChaosConfig(kills=((1, 2),))
        with pytest.raises(ValueError):
            ShardChaosConfig(kills=((1, 5, 4),))  # stop <= start
        with pytest.raises(ValueError):
            ShardChaosConfig(kills=((-1, 0, 2),))


# ---------------------------------------------------------------------------
# snapshot version-mismatch reject paths (the migration's schema gate)
# ---------------------------------------------------------------------------


class TestSnapshotVersionRejects:
    def _ingest(self):
        eng = FleetFusedIngest(_params(), 2, beams=BEAMS, buckets=(4,))
        return eng, eng.snapshot_stream(0)

    def test_ingest_forward_version_rejected_state_untouched(self):
        eng, snap = self._ingest()
        before = eng.snapshot_stream(1)
        fwd = dict(snap)
        fwd["version"] = np.asarray(
            INGEST_STREAM_SNAPSHOT_VERSION + 1, np.int32
        )
        assert not eng.restore_stream(1, fwd)
        after = eng.snapshot_stream(1)
        for k in before:
            if before[k].dtype.kind == "f":
                # the fresh lane's timestamp base is NaN (= no base)
                assert np.array_equal(
                    before[k], after[k], equal_nan=True
                ), k
            else:
                assert np.array_equal(before[k], after[k]), k

    def test_ingest_missing_version_rejected(self):
        eng, snap = self._ingest()
        missing = {k: v for k, v in snap.items() if k != "version"}
        assert not eng.restore_stream(0, missing)

    def _mapper(self):
        m = FleetMapper(_map_params(map_backend="fused"), 2, beams=64)
        pts = np.random.default_rng(1).uniform(-2, 2, (2, 64, 2))
        m.submit_points(
            pts.astype(np.float32), np.ones((2, 64), bool),
            np.ones((2,), np.int32),
        )
        return m, m.snapshot_stream(0)

    def test_mapper_forward_version_rejected_state_untouched(self):
        m, snap = self._mapper()
        before = m.snapshot_stream(1)
        fwd = dict(snap)
        fwd["version"] = np.asarray(MAP_STATE_VERSION + 1, np.int32)
        assert not m.restore_stream(1, fwd)
        after = m.snapshot_stream(1)
        for k in MAP_KEYS:
            assert np.array_equal(before[k], after[k]), k

    def test_mapper_missing_version_rejected(self):
        m, snap = self._mapper()
        missing = {k: v for k, v in snap.items() if k != "version"}
        assert not m.restore_stream(0, missing)


# ---------------------------------------------------------------------------
# /diagnostics shard-topology rendering (pinned like stream_health)
# ---------------------------------------------------------------------------


class TestShardDiagnostics:
    def test_rendering_pinned(self):
        from rplidar_ros2_driver_tpu.node.diagnostics import (
            DiagnosticsUpdater,
        )
        from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState
        from rplidar_ros2_driver_tpu.node.publisher import (
            CollectingPublisher,
        )

        payload = {
            "shards": [
                {"state": "up", "streams": [0, 4], "reason": "",
                 "evacuations": 0, "migrations_in": 2,
                 "last_migration_tick": 15},
                {"state": "lost", "streams": [],
                 "reason": "chaos: shard killed", "evacuations": 1,
                 "migrations_in": 0, "last_migration_tick": None},
            ],
            "evacuations": 1,
            "migrations": 4,
            "readmits": 1,
            "last_migration_tick": 15,
            "unhosted": [],
        }
        upd = DiagnosticsUpdater("rig", CollectingPublisher())
        status = upd.update(
            lifecycle=LifecycleState.ACTIVE, fsm_state=None,
            port="pod", rpm=0, device_info="",
            shard_topology=payload,
        )
        assert status.values["Shard 0"] == "up [0,4]"
        assert status.values["Shard 1"] == "lost [] (chaos: shard killed)"
        assert status.values["Evacuations"] == "1"
        assert status.values["Stream Migrations"] == "4"
        assert status.values["Shard Readmissions"] == "1"
        assert status.values["Last Migration Tick"] == "15"

    def test_pod_payload_feeds_the_renderer(self):
        """failover_status() is shaped for the shard_topology surface:
        the live pod's payload renders without adaptation."""
        from rplidar_ros2_driver_tpu.node.diagnostics import (
            DiagnosticsUpdater,
        )
        from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState
        from rplidar_ros2_driver_tpu.node.publisher import (
            CollectingPublisher,
        )

        pod = ElasticFleetService(
            _pod_params(shard_count=2, map_enable=False), 4,
            shards=2, beams=BEAMS, fleet_ingest_buckets=(8,),
        )
        status = DiagnosticsUpdater("rig", CollectingPublisher()).update(
            lifecycle=LifecycleState.ACTIVE, fsm_state=None,
            port="pod", rpm=0, device_info="",
            shard_topology=pod.failover_status(),
        )
        assert status.values["Shard 0"] == "up [0,2]"
        assert status.values["Shard 1"] == "up [1,3]"
        assert status.values["Last Migration Tick"] == "n/a"


# ---------------------------------------------------------------------------
# service seams
# ---------------------------------------------------------------------------


class TestElasticServiceSeams:
    def test_auto_lanes_and_single_shard(self):
        pod = ElasticFleetService(
            _pod_params(shard_count=2, map_enable=False), 4,
            shards=2, beams=BEAMS, fleet_ingest_buckets=(8,),
        )
        assert pod.topology.lanes == 4  # ceil(4 / (2-1))
        solo = ElasticFleetService(
            _pod_params(shard_count=1, map_enable=False), 3,
            shards=1, beams=BEAMS, fleet_ingest_buckets=(8,),
        )
        assert solo.topology.lanes == 3  # no failover headroom to mint

    def test_host_backend_rejected(self):
        with pytest.raises(ValueError, match="fused"):
            ElasticFleetService(
                _pod_params(shard_count=2, map_enable=False,
                            fleet_ingest_backend="host"),
                4, shards=2, beams=BEAMS,
            )

    def test_migration_before_precompile_refused(self):
        pod = ElasticFleetService(
            _pod_params(shard_count=2, map_enable=False), 4,
            shards=2, beams=BEAMS, fleet_ingest_buckets=(8,),
        )
        with pytest.raises(RuntimeError, match="precompile"):
            pod._restore_into(0, 0, 0, None)

    def test_wrong_item_count_rejected(self):
        pod = ElasticFleetService(
            _pod_params(shard_count=2, map_enable=False), 4,
            shards=2, beams=BEAMS, fleet_ingest_buckets=(8,),
        )
        with pytest.raises(ValueError, match="per-stream"):
            pod.submit_bytes([None] * 3)
