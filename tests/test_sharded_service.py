"""Multi-stream ShardedFilterService on the virtual 8-device CPU mesh.

Key property: a stream processed through the sharded multi-stream service
must produce bit-identical outputs to the same scans through the
single-device ScanFilterChain.
"""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain
from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh


def _params(**kw) -> DriverParams:
    base = dict(
        dummy_mode=True,
        filter_backend="cpu",
        filter_chain=("clip", "median", "voxel"),
        filter_window=4,
        voxel_grid_size=32,
    )
    base.update(kw)
    return DriverParams(**base)


def _scan(k: int, points: int = 300) -> dict:
    rng = np.random.default_rng(k)
    return {
        "angle_q14": ((np.arange(points) * 65536) // points).astype(np.int32),
        "dist_q2": (rng.uniform(0.3, 8.0, points) * 4000).astype(np.int32),
        "quality": np.full(points, 180, np.int32),
        "flag": None,
    }


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)  # conftest forces 8 virtual CPU devices


class TestService:
    def test_matches_single_stream_chain(self, mesh):
        svc = ShardedFilterService(_params(), streams=4, mesh=mesh, beams=128)
        chains = [ScanFilterChain(_params(), beams=128) for _ in range(4)]
        for tick in range(6):
            scans = [_scan(100 * s + tick) for s in range(4)]
            outs = svc.submit(scans)
            for s in range(4):
                ref = chains[s].process_raw(
                    scans[s]["angle_q14"], scans[s]["dist_q2"], scans[s]["quality"]
                )
                np.testing.assert_array_equal(
                    np.asarray(outs[s].ranges), np.asarray(ref.ranges)
                )
                np.testing.assert_array_equal(
                    np.asarray(outs[s].voxel), np.asarray(ref.voxel)
                )

    def test_idle_stream_returns_none_but_advances(self, mesh):
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        outs = svc.submit([_scan(1), None])
        assert outs[0] is not None and outs[1] is None
        # idle stream advanced its cursor in lock-step
        snap = svc.snapshot()
        assert snap["cursor"][0] == snap["cursor"][1] == 1

    def test_wrong_stream_count_rejected(self, mesh):
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        with pytest.raises(ValueError):
            svc.submit([_scan(1)])

    def test_capacity_overflow_rejected(self, mesh):
        svc = ShardedFilterService(
            _params(), streams=2, mesh=mesh, beams=128, capacity=256
        )
        with pytest.raises(ValueError):
            svc.submit([_scan(1, points=300), None])

    def test_snapshot_restore_roundtrip(self, mesh):
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit([_scan(1), _scan(2)])
        snap = svc.snapshot()

        svc2 = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        assert svc2.restore(snap)
        for k, v in svc2.snapshot().items():
            np.testing.assert_array_equal(v, snap[k])
        # continued processing agrees
        a = svc.submit([_scan(3), _scan(4)])
        b = svc2.submit([_scan(3), _scan(4)])
        np.testing.assert_array_equal(
            np.asarray(a[0].voxel), np.asarray(b[0].voxel)
        )

    def test_restore_rejects_wrong_geometry(self, mesh):
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit([_scan(1), _scan(2)])
        snap = svc.snapshot()
        other = ShardedFilterService(_params(filter_window=8), streams=2, mesh=mesh, beams=128)
        assert not other.restore(snap)

    def test_submit_pipelined_is_submit_shifted_by_one_tick(self, mesh):
        """The pipelined fleet tick returns exactly submit's outputs
        delayed by one tick (all-None first), flush_pipelined drains the
        final tick, and idle-stream None slots follow each tick's OWN
        live mask."""
        svc_p = ShardedFilterService(_params(), streams=4, mesh=None, beams=128)
        svc_s = ShardedFilterService(_params(), streams=4, mesh=None, beams=128)
        # mesh=None default also exercises the service's own mesh pick
        ticks = [
            [_scan(1), None, _scan(3), _scan(4)],
            [None, _scan(5), _scan(6), None],
            [_scan(7), _scan(8), None, _scan(9)],
        ]
        outs_s = [svc_s.submit(t) for t in ticks]
        outs_p = [svc_p.submit_pipelined(t) for t in ticks]
        assert outs_p[0] == [None, None, None, None]
        for k in range(1, len(ticks)):
            for a, b in zip(outs_p[k], outs_s[k - 1]):
                assert (a is None) == (b is None)
                if a is not None:
                    np.testing.assert_array_equal(a.ranges, b.ranges)
                    np.testing.assert_array_equal(a.voxel, b.voxel)
        tail = svc_p.flush_pipelined()
        for a, b in zip(tail, outs_s[-1]):
            assert (a is None) == (b is None)
            if a is not None:
                np.testing.assert_array_equal(a.ranges, b.ranges)
        assert svc_p.flush_pipelined() is None

    def test_submit_pipelined_restore_clears_pending(self, mesh):
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit_pipelined([_scan(1), _scan(2)])
        svc.restore(None)
        assert svc.flush_pipelined() is None

    def test_submit_pipelined_restore_drops_next_tick_output(self, mesh):
        """A restore between ticks drops the pre-restore pending tick
        (the NEXT pipelined tick returns all-None, it does not republish
        pre-restore outputs), and the post-restore stream then resumes
        normally — the deterministic statement of the epoch guard that
        the concurrency hammer exercises under racing."""
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        ref = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit_pipelined([_scan(1), _scan(2)])
        svc.restore(None)
        assert svc.submit_pipelined([_scan(3), _scan(4)]) == [None, None]
        ref.submit([_scan(1), _scan(2)])
        ref.restore(None)
        ref_out = ref.submit([_scan(3), _scan(4)])
        out = svc.submit_pipelined([_scan(5), _scan(6)])
        np.testing.assert_array_equal(out[0].ranges, ref_out[0].ranges)

    def test_submit_pipelined_dispatch_failure_keeps_pending(self, mesh):
        """A failed tick dispatch after the previous tick was popped must
        re-stash it so the drain can still publish it."""
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        ref = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit_pipelined([_scan(1), _scan(2)])
        ref_out = ref.submit([_scan(1), _scan(2)])
        step, svc._step = svc._step, None  # next tick: TypeError
        with pytest.raises(TypeError):
            svc.submit_pipelined([_scan(3), _scan(4)])
        svc._step = step
        tail = svc.flush_pipelined()
        assert tail is not None
        np.testing.assert_array_equal(tail[0].ranges, ref_out[0].ranges)

    def test_submit_pipelined_fetch_failure_keeps_pending(self, mesh):
        """If the device->host materialize of the previous tick itself
        fails, the pending tick must be re-stashed so the drain can retry
        the fetch — not dropped."""
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        ref = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit_pipelined([_scan(1), _scan(2)])
        ref_out = ref.submit([_scan(1), _scan(2)])

        def boom(*a, **k):
            raise RuntimeError("fetch died")

        materialize = svc._materialize
        svc._materialize = boom
        with pytest.raises(RuntimeError):
            svc.submit_pipelined([_scan(3), _scan(4)])
        svc._materialize = materialize
        tail = svc.flush_pipelined()
        assert tail is not None
        np.testing.assert_array_equal(tail[0].ranges, ref_out[0].ranges)

    def test_submit_local_pipelined_matches_submit_local_shifted(self, mesh):
        """The pipelined multi-controller tick must return submit_local's
        outputs shifted by exactly one tick, with the flush draining the
        final in-flight tick (single-process here; the 2-process parity
        lives in test_multiprocess.py)."""
        svc_p = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc_s = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        ticks = [[_scan(2 * k), _scan(2 * k + 1)] for k in range(4)]
        outs_s = [svc_s.submit_local(t) for t in ticks]
        outs_p = [svc_p.submit_local_pipelined(t) for t in ticks]
        assert outs_p[0] == [None, None]
        for k in range(1, len(ticks)):
            for a, b in zip(outs_p[k], outs_s[k - 1]):
                np.testing.assert_array_equal(a.ranges, b.ranges)
                np.testing.assert_array_equal(a.voxel, b.voxel)
        tail = svc_p.flush_pipelined()
        for a, b in zip(tail, outs_s[-1]):
            np.testing.assert_array_equal(a.ranges, b.ranges)
        assert svc_p.flush_pipelined() is None

    def test_submit_local_pipelined_collect_failure_drops_not_raises(self, mesh):
        """A previous-tick collect fault must NOT raise out of the
        pipelined local tick (that would abort this process before the
        collective while peers block inside theirs): the tick is dropped
        with a warning, this tick dispatches normally, and the stream
        continues shifted."""
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        ref = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit_local_pipelined([_scan(1), _scan(2)])
        ref.submit_local([_scan(1), _scan(2)])

        def boom(*a, **k):
            raise RuntimeError("fetch died")

        # patch _materialize — the shared leaf both collectors funnel
        # through (the stashed collector name resolves via getattr at
        # collect time, so patching _collect_local would work too; the
        # leaf also covers the controller-global path)
        materialize = svc._materialize
        svc._materialize = boom
        out = svc.submit_local_pipelined([_scan(3), _scan(4)])
        svc._materialize = materialize
        assert out == [None, None]  # tick 1 dropped, no exception
        ref_out2 = ref.submit_local([_scan(3), _scan(4)])
        out3 = svc.submit_local_pipelined([_scan(5), _scan(6)])
        np.testing.assert_array_equal(out3[0].ranges, ref_out2[0].ranges)

    def test_submit_local_pipelined_dispatch_failure_keeps_collected_tick(
        self, mesh
    ):
        """Collect of tick N succeeds, then tick N+1's dispatch dies: the
        raise discards the collected outputs, so the pending tuple must be
        re-stashed (unconditionally, like submit_pipelined) and the flush
        re-collect is tick N's only publish."""
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        ref = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit_local_pipelined([_scan(1), _scan(2)])
        ref_out = ref.submit_local([_scan(1), _scan(2)])
        step, svc._step = svc._step, None  # next dispatch: TypeError
        with pytest.raises(TypeError):
            svc.submit_local_pipelined([_scan(3), _scan(4)])
        svc._step = step
        tail = svc.flush_pipelined()
        assert tail is not None
        np.testing.assert_array_equal(tail[0].ranges, ref_out[0].ranges)

    def test_submit_local_truncates_oversized_scan(self, mesh):
        """An oversized scan must not raise out of submit_local — a
        per-process ValueError before the collective would hang every
        peer process inside theirs.  It is truncated to capacity
        (head-keep, the assembler's overflow policy) and the tick
        proceeds; submit with the pre-truncated scan is the oracle."""
        cap = 256
        svc = ShardedFilterService(
            _params(), streams=4, mesh=mesh, beams=128, capacity=cap
        )
        ref = ShardedFilterService(
            _params(), streams=4, mesh=mesh, beams=128, capacity=cap
        )
        big = _scan(7, points=cap + 50)
        big["ts0"] = 1.5  # scalar metadata (assembler-shaped dicts carry it)
        clipped = {
            k: (v[:cap] if k != "ts0" and v is not None else v)
            for k, v in big.items()
        }
        small_1, small_3 = _scan(1, points=200), _scan(3, points=200)
        out = svc.submit_local([big, small_1, None, small_3])
        out_ref = ref.submit([clipped, small_1, None, small_3])
        for a, b in zip(out, out_ref):
            assert (a is None) == (b is None)
            if a is not None:
                np.testing.assert_array_equal(a.ranges, b.ranges)

    def test_submit_local_degrades_malformed_scan_to_idle(self, mesh, caplog):
        """Any packing failure beyond oversize (e.g. mismatched field
        lengths) must also not raise out of submit_local pre-collective:
        the malformed scan becomes an all-masked idle row with a warning
        and the other streams' tick proceeds normally."""
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        ref = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        bad = _scan(5)
        bad["dist_q2"] = bad["dist_q2"][:-7]  # truncated capture
        good = _scan(9)
        with caplog.at_level("WARNING", logger="rplidar_tpu.service"):
            out = svc.submit_local([bad, good])
        assert any("malformed" in r.message for r in caplog.records)
        # the peer stream is unaffected; oracle = submit with bad idle.
        # (submit_local still returns an output object for the bad slot —
        # it carries the all-masked frame's result, matching a None tick.)
        out_ref = ref.submit([None, good])
        np.testing.assert_array_equal(out[1].ranges, out_ref[1].ranges)
        snap, snap_ref = svc.snapshot(), ref.snapshot()
        np.testing.assert_array_equal(snap["range_window"], snap_ref["range_window"])
        # a scan missing a wire field entirely (KeyError class) likewise
        # degrades to idle instead of escaping pre-collective
        no_quality = {k: v for k, v in _scan(6).items() if k != "quality"}
        out2 = svc.submit_local([no_quality, _scan(8)])
        out2_ref = ref.submit([None, _scan(8)])
        np.testing.assert_array_equal(out2[1].ranges, out2_ref[1].ranges)
        # oversize + mismatched lengths = still malformed, NOT clipped
        # into accidental agreement (clipping would mask the mismatch)
        over_bad = _scan(4, points=svc.capacity + 50)
        over_bad["dist_q2"] = over_bad["dist_q2"][:-6]
        out3 = svc.submit_local([over_bad, _scan(10)])
        out3_ref = ref.submit([None, _scan(10)])
        np.testing.assert_array_equal(out3[1].ranges, out3_ref[1].ranges)
        np.testing.assert_array_equal(
            svc.snapshot()["range_window"], ref.snapshot()["range_window"]
        )


class TestOrbaxCheckpoint:
    @pytest.fixture(autouse=True)
    def _needs_orbax(self):
        pytest.importorskip("orbax.checkpoint")

    def test_sharded_save_restore_roundtrip(self, mesh, tmp_path):
        """Orbax round-trip of the SHARDED state (no host gather): the
        restored service's shards land on its mesh and processing agrees."""
        path = str(tmp_path / "ckpt")
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit([_scan(1), _scan(2)])
        svc.save_sharded(path)

        svc2 = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        assert svc2.load_sharded(path)
        for k, v in svc2.snapshot().items():
            np.testing.assert_array_equal(v, svc.snapshot()[k], k)
        a = svc.submit([_scan(3), _scan(4)])
        b = svc2.submit([_scan(3), _scan(4)])
        np.testing.assert_array_equal(np.asarray(a[1].voxel), np.asarray(b[1].voxel))

    def test_sharded_restore_rejects_wrong_geometry(self, mesh, tmp_path):
        path = str(tmp_path / "ckpt")
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit([_scan(1), _scan(2)])
        svc.save_sharded(path)

        other = ShardedFilterService(
            _params(filter_window=8), streams=2, mesh=mesh, beams=128
        )
        other.submit([_scan(7), _scan(8)])
        before = other.snapshot()
        assert not other.load_sharded(path)
        # absence is also a clean no-op
        assert not other.load_sharded(str(tmp_path / "missing"))
        # rejected restores left the current state untouched
        for k, v in other.snapshot().items():
            np.testing.assert_array_equal(v, before[k], k)

    def test_save_rotation_keeps_previous_on_crash_window(self, mesh, tmp_path):
        """If a crash strands the previous checkpoint at .old (between the
        two rotation renames), restore recovers it instead of failing."""
        import shutil

        path = str(tmp_path / "ckpt")
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit([_scan(1), _scan(2)])
        svc.save_sharded(path)
        shutil.move(path, path + ".old")  # simulate the crash window

        svc2 = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        assert svc2.load_sharded(path)
        for k, v in svc2.snapshot().items():
            np.testing.assert_array_equal(v, svc.snapshot()[k], k)

    def test_overwrite_in_place(self, mesh, tmp_path):
        """Repeated saves to one path keep working and keep the newest."""
        path = str(tmp_path / "ckpt")
        svc = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        svc.submit([_scan(1), _scan(2)])
        svc.save_sharded(path)
        svc.submit([_scan(3), _scan(4)])
        svc.save_sharded(path)

        svc2 = ShardedFilterService(_params(), streams=2, mesh=mesh, beams=128)
        assert svc2.load_sharded(path)
        np.testing.assert_array_equal(
            svc2.snapshot()["voxel_acc"], svc.snapshot()["voxel_acc"]
        )
        assert not (tmp_path / "ckpt.old").exists()

    def test_sharded_restore_across_mesh_shapes(self, tmp_path):
        """A checkpoint saved on one mesh shape restores onto another —
        the global arrays are mesh-agnostic (save on (2,4), load on (4,2))."""
        path = str(tmp_path / "ckpt")
        m_a = make_mesh(8, stream=2)
        m_b = make_mesh(8, stream=4)
        svc = ShardedFilterService(_params(), streams=4, mesh=m_a, beams=128)
        svc.submit([_scan(s) for s in range(4)])
        svc.save_sharded(path)

        svc2 = ShardedFilterService(_params(), streams=4, mesh=m_b, beams=128)
        assert svc2.load_sharded(path)
        for k, v in svc2.snapshot().items():
            np.testing.assert_array_equal(v, svc.snapshot()[k], k)

    def test_submit_local_single_process_matches_submit(self, mesh):
        """Single-process, submit_local covers the full stream range and
        must be tick-for-tick identical to submit (same state trajectory,
        same outputs) — the degenerate case of the multi-controller path
        (the real 2-process case lives in test_multiprocess.py)."""
        svc_a = ShardedFilterService(_params(), streams=4, mesh=mesh, beams=128)
        svc_b = ShardedFilterService(_params(), streams=4, mesh=mesh, beams=128)
        for tick in range(3):
            scans = [
                _scan(10 * tick + s) if (tick + s) % 3 else None
                for s in range(4)
            ]
            out_a = svc_a.submit(scans)
            out_b = svc_b.submit_local(scans)
            for a, b in zip(out_a, out_b):
                assert (a is None) == (b is None)
                if a is None:
                    continue
                np.testing.assert_array_equal(a.ranges, b.ranges)
                np.testing.assert_array_equal(a.voxel, b.voxel)
                np.testing.assert_array_equal(a.points_xy, b.points_xy)
        for k, v in svc_b.snapshot().items():
            np.testing.assert_array_equal(v, svc_a.snapshot()[k], k)
