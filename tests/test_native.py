"""Native runtime tests: codec parity vs the Python decoder, channel
loopbacks (TCP/UDP/pty-serial), transceiver streaming + hot-unplug error
propagation.  Skipped wholesale if the toolchain can't build the library."""

import os
import random
import socket
import struct
import threading
import time

import pytest

from rplidar_ros2_driver_tpu import native as native_mod
from rplidar_ros2_driver_tpu.protocol.codec import AnsHeader, ResponseDecoder, encode_command

pytestmark = pytest.mark.skipif(
    not native_mod.available(), reason="native library unavailable"
)


def _frame(ans_type: int, payloads: list[bytes], is_loop: bool = False) -> bytes:
    """One response header + payload(s) (loop mode repeats payloads)."""
    out = AnsHeader(ans_type=ans_type, payload_len=len(payloads[0]), is_loop=is_loop).encode()
    for p in payloads:
        out += p
    return out


class TestCodecParity:
    def test_encode_command_matches_python(self):
        from rplidar_ros2_driver_tpu.native.runtime import encode_command as native_encode

        for cmd, payload in [
            (0x25, b""),
            (0x20, b""),
            (0x50, b""),
            (0x82, bytes(5)),
            (0x84, struct.pack("<I", 0x70)),
            (0xF0, struct.pack("<H", 660)),
            (0xA8, struct.pack("<H", 600)),
        ]:
            assert native_encode(cmd, payload) == encode_command(cmd, payload)

    def test_decoder_parity_fuzz(self):
        """Random non-loop frames with sync-free noise between them, fed in
        random chunk sizes to both decoders — identical message streams.
        (Loop mode swallows subsequent headers by design, so it is covered
        separately in test_loop_mode_and_reset.)"""
        from rplidar_ros2_driver_tpu.native.runtime import NativeDecoder

        rng = random.Random(7)

        def noise(n):  # no 0xA5 -> cannot form a sync pair
            return bytes([rng.randrange(0, 0xA0) for _ in range(n)])

        stream = bytearray(noise(16))
        expect_types = []
        for _ in range(40):
            ans_type = rng.choice([0x04, 0x06, 0x15, 0x20, 0x21])
            n = rng.randrange(0, 24)
            payloads = [bytes([rng.randrange(256) for _ in range(n)])] if n else [b""]
            stream += _frame(ans_type, payloads, is_loop=False)
            expect_types.append(ans_type)
            stream += noise(rng.randrange(0, 6))

        nat = NativeDecoder()
        py = ResponseDecoder()
        data = bytes(stream)
        i = 0
        while i < len(data):
            step = rng.randrange(1, 17)
            nat.feed(data[i : i + step])
            py.feed(data[i : i + step])
            i += step
        nat_msgs = [(t, p) for (t, p, _l) in nat.drain()]
        py_msgs = [(t, p) for (t, p, _l) in py.messages]
        assert nat_msgs == py_msgs
        assert [t for (t, _p) in py_msgs] == expect_types

    def test_loop_mode_and_reset(self):
        from rplidar_ros2_driver_tpu.native.runtime import NativeDecoder

        nat = NativeDecoder()
        nat.feed(_frame(0x82, [bytes(84), bytes(84)], is_loop=True))
        msgs = nat.drain()
        assert len(msgs) == 2
        assert all(t == 0x82 and loop for (t, _p, loop) in msgs)
        # without reset, a new header is swallowed as loop payload bytes
        nat.reset()
        nat.feed(_frame(0x04, [bytes(20)]))
        msgs = nat.drain()
        assert len(msgs) == 1 and msgs[0][0] == 0x04 and not msgs[0][2]

    def test_header_only_packet(self):
        from rplidar_ros2_driver_tpu.native.runtime import NativeDecoder

        nat = NativeDecoder()
        nat.feed(_frame(0x21, [b""]))
        msgs = nat.drain()
        assert msgs == [(0x21, b"", False)]


class TestTcpChannel:
    def _server(self, payload: bytes, accept_then=None):
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def run():
            conn, _ = srv.accept()
            conn.sendall(payload)
            if accept_then:
                accept_then(conn)
            else:
                time.sleep(0.2)
                conn.close()
            srv.close()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return port, t

    def test_tcp_roundtrip(self):
        from rplidar_ros2_driver_tpu.native.runtime import NativeChannel

        echo: list[bytes] = []

        def read_back(conn):
            conn.settimeout(2.0)
            try:
                echo.append(conn.recv(64))
            except socket.timeout:
                echo.append(b"")
            conn.close()

        port, t = self._server(b"hello-lidar", accept_then=read_back)
        ch = NativeChannel("tcp", "127.0.0.1", port=port)
        assert ch.open()
        got = b""
        deadline = time.monotonic() + 2
        while len(got) < 11 and time.monotonic() < deadline:
            chunk = ch.read(64, timeout_ms=500)
            if chunk:
                got += chunk
        assert got == b"hello-lidar"
        assert ch.write(b"pong") == 4
        t.join(3)
        assert echo and echo[0] == b"pong"
        ch.close()

    def test_read_timeout_and_cancel(self):
        from rplidar_ros2_driver_tpu.native.runtime import NativeChannel

        port, t = self._server(b"", accept_then=lambda c: time.sleep(0.5))
        ch = NativeChannel("tcp", "127.0.0.1", port=port)
        assert ch.open()
        t0 = time.monotonic()
        assert ch.read(16, timeout_ms=100) is None  # timeout
        assert 0.05 < time.monotonic() - t0 < 1.0
        canceller = threading.Timer(0.1, ch.cancel)
        canceller.start()
        assert ch.read(16, timeout_ms=5000) == b""  # cancelled -> closed signal
        ch.close()
        t.join(3)

    def test_connect_refused(self):
        from rplidar_ros2_driver_tpu.native.runtime import NativeChannel

        ch = NativeChannel("tcp", "127.0.0.1", port=1)  # nothing listens
        assert not ch.open()


class TestUdpChannel:
    def test_udp_roundtrip(self):
        from rplidar_ros2_driver_tpu.native.runtime import NativeChannel

        srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        srv.bind(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        ch = NativeChannel("udp", "127.0.0.1", port=port)
        assert ch.open()
        assert ch.write(b"ping") == 4
        data, addr = srv.recvfrom(64)
        assert data == b"ping"
        srv.sendto(b"pong", addr)
        got = ch.read(64, timeout_ms=1000)
        assert got == b"pong"
        ch.close()
        srv.close()


class TestSerialChannel:
    def test_pty_roundtrip(self):
        from rplidar_ros2_driver_tpu.native.runtime import NativeChannel

        master, slave = os.openpty()
        try:
            ch = NativeChannel("serial", os.ttyname(slave), baud=115200)
            if not ch.open():
                pytest.skip("pty rejects termios2 configuration on this kernel")
            os.write(master, b"\xa5\x5a123")
            got = b""
            deadline = time.monotonic() + 2
            while len(got) < 5 and time.monotonic() < deadline:
                chunk = ch.read(16, timeout_ms=200)
                if chunk:
                    got += chunk
            assert got == b"\xa5\x5a123"
            assert ch.write(b"ok") == 2
            assert os.read(master, 16) == b"ok"
            ch.close()
        finally:
            os.close(master)
            os.close(slave)


class TestTransceiver:
    def _lidar_server(self, frames: bytes, close_after: float = 0.5):
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        received: list[bytes] = []

        def run():
            conn, _ = srv.accept()
            conn.settimeout(1.0)
            try:
                received.append(conn.recv(64))  # the start-scan command
            except socket.timeout:
                pass
            conn.sendall(frames)
            time.sleep(close_after)
            conn.close()
            srv.close()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return port, t, received

    def test_stream_and_error_propagation(self):
        from rplidar_ros2_driver_tpu.native.runtime import (
            ChannelError,
            NativeChannel,
            NativeTransceiver,
        )

        payloads = [bytes([i] * 84) for i in range(5)]
        frames = _frame(0x82, payloads, is_loop=True)
        port, t, received = self._lidar_server(frames, close_after=0.3)

        ch = NativeChannel("tcp", "127.0.0.1", port=port)
        tx = NativeTransceiver(ch)
        assert tx.start()
        assert tx.send(encode_command(0x20))
        got = []
        with pytest.raises(ChannelError):
            for _ in range(10):
                m = tx.wait_message(timeout_ms=2000)
                if m is None:
                    continue
                got.append(m)
        # 5 loop payloads arrived before the peer hung up
        assert [p for (_t, p, _l) in got] == payloads
        assert tx.had_error
        tx.stop()
        t.join(3)
        assert received and received[0] == encode_command(0x20)

    def test_rx_thread_priority_elevation_best_effort(self):
        """The rx thread attempts the reference's PRIORITY_HIGH (SCHED_RR,
        arch/linux/thread.hpp:64-120) and must FALL BACK silently when
        unprivileged: after start the reported class is one of
        {0 default, 1 nice, 2 SCHED_RR} — never a failure — and streaming
        still works."""
        from rplidar_ros2_driver_tpu.native.runtime import NativeChannel, NativeTransceiver

        frames = _frame(0x81, [bytes(5)], is_loop=True)
        port, t, _ = self._lidar_server(frames, close_after=0.8)
        ch = NativeChannel("tcp", "127.0.0.1", port=port)
        tx = NativeTransceiver(ch)
        assert tx.rx_priority == -1  # not started yet
        assert tx.start()
        m = tx.wait_message(timeout_ms=2000)
        assert m is not None
        assert tx.rx_priority in (0, 1, 2), tx.rx_priority
        # the engine relays the achieved class (bench artifacts record it)
        from rplidar_ros2_driver_tpu.protocol.engine import CommandEngine

        eng = CommandEngine.__new__(CommandEngine)
        eng._tx = tx
        assert eng.rx_priority == tx.rx_priority
        tx.stop()
        t.join(3)

    def test_rx_no_elevate_knob_forces_default_policy(self, monkeypatch):
        """RPL_RX_NO_ELEVATE=1 (the RR-vs-default A/B knob, read by the
        rx thread at elevation time) must skip elevation entirely —
        reported class exactly 0 — and leave streaming intact."""
        from rplidar_ros2_driver_tpu.native.runtime import (
            NativeChannel,
            NativeTransceiver,
        )

        frames = _frame(0x81, [bytes(5)], is_loop=True)
        port, t, _ = self._lidar_server(frames, close_after=0.8)
        ch = NativeChannel("tcp", "127.0.0.1", port=port)
        tx = NativeTransceiver(ch)
        monkeypatch.setenv("RPL_RX_NO_ELEVATE", "1")
        try:
            assert tx.start()
            m = tx.wait_message(timeout_ms=2000)
            assert m is not None
            assert tx.rx_priority == 0, tx.rx_priority
        finally:
            tx.stop()
            t.join(3)

    def test_reset_decoder_between_modes(self):
        from rplidar_ros2_driver_tpu.native.runtime import NativeChannel, NativeTransceiver

        first = _frame(0x81, [bytes(5)], is_loop=True)
        port, t, _ = self._lidar_server(first, close_after=0.8)
        ch = NativeChannel("tcp", "127.0.0.1", port=port)
        tx = NativeTransceiver(ch)
        assert tx.start()
        m = tx.wait_message(timeout_ms=2000)
        assert m and m[0] == 0x81
        tx.reset_decoder()  # as the driver does on stop/exitLoopMode
        tx.stop()
        t.join(3)


class TestDecoderRobustness:
    def test_corrupted_giant_size_header_resyncs(self):
        """A noise header claiming a ~1 GiB payload must not swallow the
        stream — the decoder resyncs and the next real frame decodes."""
        import struct

        from rplidar_ros2_driver_tpu.native.runtime import NativeDecoder

        d = NativeDecoder()
        d.feed(b"\xa5\x5a" + struct.pack("<I", 0x3FFFFFFF) + b"\x04")
        d.feed(b"\xa5\x5a" + struct.pack("<I", 3) + b"\x06" + b"\x00\x01\x02")
        msgs = d.drain()
        assert len(msgs) == 1
        ans_type, payload, is_loop = msgs[0]
        assert ans_type == 0x06 and payload == b"\x00\x01\x02" and not is_loop

    def test_max_sane_payload_accepted(self):
        import struct

        from rplidar_ros2_driver_tpu.native.runtime import NativeDecoder

        d = NativeDecoder()
        body = bytes(8192)
        d.feed(b"\xa5\x5a" + struct.pack("<I", 8192) + b"\x20" + body)
        msgs = d.drain()
        assert len(msgs) == 1 and len(msgs[0][1]) == 8192
