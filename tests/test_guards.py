"""Runtime sentinels (utils/guards): zero recompiles and zero implicit
transfers in STEADY STATE for all four fused engines.

Every engine ships a precompile() and an explicit device_put staging
path precisely so its live loop never pays an in-loop XLA compile or an
undeclared transfer.  The bench decompositions assert the dispatch
counts; these tests pin the other half of the contract at tier-1: after
warmup, the hot loop runs under ``jax_transfer_guard="disallow"`` with a
compile listener attached, and ANY violation raises.

Engines covered (the satellite contract):
  * FusedIngest          — single-stream fused ingest, frame batches
  * FleetFusedIngest     — per-tick fleet fused ingest
  * FleetFusedIngest (T) — super-tick backlog drain
  * FleetMapper          — fused SLAM front-end ticks
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest, FusedIngest
from rplidar_ros2_driver_tpu.mapping.mapper import FleetMapper
from rplidar_ros2_driver_tpu.protocol.constants import Ans
from rplidar_ros2_driver_tpu.utils import guards

from test_fused_ingest import BEAMS, _params
from test_fleet_fused_ingest import _mk_ticks
from test_live_decode import _make_stream

DENSE = int(Ans.MEASUREMENT_DENSE_CAPSULED)


# ---------------------------------------------------------------------------
# the guard primitives themselves
# ---------------------------------------------------------------------------


class TestGuardPrimitives:
    def test_detects_fresh_compile(self):
        fn = jax.jit(lambda x: x * 2 + 1)
        x = jax.device_put(np.ones((7,), np.float32))
        with pytest.raises(guards.RecompileError) as e:
            with guards.assert_no_recompile(tag="unit"):
                fn(x).block_until_ready()
        assert "unit" in str(e.value)

    def test_passes_when_warm(self):
        fn = jax.jit(lambda x: x - 3)
        x = jax.device_put(np.ones((5,), np.float32))
        fn(x).block_until_ready()
        with guards.assert_no_recompile() as rec:
            fn(x).block_until_ready()
        assert rec.compiles == []

    def test_max_compiles_budget(self):
        fn = jax.jit(lambda x: x / 2)
        x = jax.device_put(np.ones((11,), np.float32))
        with guards.assert_no_recompile(max_compiles=8):
            fn(x).block_until_ready()  # within budget: no raise

    def test_blocks_implicit_numpy_jit_transfer(self):
        fn = jax.jit(lambda x: x + 1)
        xnp = np.ones((3,), np.float32)
        fn(xnp)  # warm OUTSIDE the guard
        with pytest.raises(Exception, match="[Dd]isallowed"):
            with guards.no_implicit_transfers():
                fn(xnp)

    def test_allows_explicit_device_put(self):
        fn = jax.jit(lambda x: x + 1)
        xd = jax.device_put(np.ones((3,), np.float32))
        fn(xd)
        with guards.no_implicit_transfers():
            out = fn(jax.device_put(np.ones((3,), np.float32)))
            assert float(jax.device_get(out)[0]) == 2.0

    def test_steady_state_combines_both(self):
        fn = jax.jit(lambda x: x * x)
        xd = jax.device_put(np.ones((9,), np.float32))
        fn(xd)
        with guards.steady_state(tag="combo") as rec:
            fn(xd).block_until_ready()
        assert rec.compiles == []


# ---------------------------------------------------------------------------
# engine steady states
# ---------------------------------------------------------------------------


def _timed(frames, t0=100.0, dt=0.002):
    t = t0
    out = []
    for f in frames:
        t += dt
        out.append((f, t))
    return out


class TestFusedIngestSteadyState:
    def test_zero_recompiles_zero_implicit_transfers(self):
        eng = FusedIngest(_params(), beams=BEAMS, buckets=(4,), max_queue=64)
        eng.precompile(DENSE)
        frames = _make_stream(
            DENSE, 96, np.random.default_rng(7),
            syncs=(0, 17, 34, 51, 68, 85),
        )
        items = _timed(frames)
        # warmup: stream activation + first live dispatches
        for i in range(0, 32, 4):
            eng.on_measurement_batch(DENSE, items[i : i + 4])
        eng.flush()
        with guards.steady_state(tag="FusedIngest"):
            for i in range(32, 96, 4):
                eng.on_measurement_batch(DENSE, items[i : i + 4])
            outs = eng.flush()
        # the guard run must have processed real work, not an idle loop
        assert eng.scans_completed >= 3
        assert any(outs)


class TestFleetFusedIngestSteadyState:
    def test_zero_recompiles_zero_implicit_transfers(self):
        s = 2
        eng = FleetFusedIngest(
            _params(), s, beams=BEAMS, buckets=(4,), max_revs=6
        )
        eng.precompile([DENSE] * s)
        streams = [
            (DENSE, _make_stream(DENSE, 64, np.random.default_rng(i),
                                 syncs=(0, 17, 34, 51)))
            for i in range(s)
        ]
        ticks = _mk_ticks(streams, np.random.default_rng(99), idle_prob=0.0)
        cut = max(2, len(ticks) // 3)
        for tick in ticks[:cut]:  # warmup ticks
            eng.submit(tick)
        with guards.steady_state(tag="FleetFusedIngest"):
            total = 0
            for tick in ticks[cut:]:
                for o in eng.submit(tick):
                    total += len(o)
        assert eng.dispatch_count >= len(ticks)
        assert total >= 1  # revolutions completed under the guard


class TestSuperTickSteadyState:
    def test_backlog_drain_zero_recompiles_zero_transfers(self):
        s, T = 2, 4
        eng = FleetFusedIngest(
            _params(), s, beams=BEAMS, buckets=(4,), max_revs=6,
            super_tick_max=T,
        )
        eng.precompile([DENSE] * s)
        streams = [
            (DENSE, _make_stream(DENSE, 96, np.random.default_rng(10 + i),
                                 syncs=(0, 17, 34, 51, 68, 85)))
            for i in range(s)
        ]
        ticks = _mk_ticks(streams, np.random.default_rng(5), idle_prob=0.0)
        cut = max(T, len(ticks) // 2)
        eng.submit_backlog(ticks[:cut])  # warmup drain
        before = eng.super_dispatches
        with guards.steady_state(tag="super-tick drain"):
            outs = eng.submit_backlog(ticks[cut:])
        assert eng.super_dispatches > before  # the drain used the T-program
        assert sum(len(o) for o in outs) >= 1


class TestAdaptiveRungSteadyState:
    def test_rung_switches_stay_in_the_compile_cache(self):
        """The adaptive scheduler's structural precondition
        (parallel/scheduler.py): every ladder rung is warmed at
        precompile — one compiled super-step per (rung, bucket) — so a
        drain sequence that switches depth mid-run (shallow, deep,
        shallow: the backlog-adaptive pick under bursty traffic) runs
        with ZERO recompiles and ZERO implicit transfers.  A rung
        switch is a compile-cache hit by construction, never a
        compile."""
        s = 2
        eng = FleetFusedIngest(
            _params(), s, beams=BEAMS, buckets=(4,), max_revs=6,
            rungs=(1, 2, 4),
        )
        assert eng.rungs == (1, 2, 4)
        eng.precompile([DENSE] * s)
        streams = [
            (DENSE, _make_stream(DENSE, 96, np.random.default_rng(20 + i),
                                 syncs=(0, 17, 34, 51, 68, 85)))
            for i in range(s)
        ]
        ticks = _mk_ticks(streams, np.random.default_rng(8), idle_prob=0.0)
        cut = max(4, len(ticks) // 3)
        eng.submit_backlog(ticks[:cut], rung=4)  # live-path warmup
        before = dict(eng.rung_dispatches)
        total = 0
        with guards.steady_state(tag="adaptive rung switches"):
            pos = cut
            for rung in (1, 4, 2, 4, 1, 2):
                if pos >= len(ticks):
                    break
                step = max(rung, 2)
                outs = eng.submit_backlog(
                    ticks[pos : pos + step], rung=rung
                )
                pos += step
                total += sum(len(o) for o in outs)
        # the guard run exercised MULTIPLE rungs, not a degenerate loop
        moved = [
            r for r in eng.rungs
            if eng.rung_dispatches[r] > before.get(r, 0)
        ]
        assert len(moved) >= 2
        assert total >= 1
        assert sum(eng.rung_dispatches.values()) == eng.dispatch_count


class TestBucketLadderSteadyState:
    def test_bucket_switches_and_offpath_snapshots_stay_steady(self):
        """PR 16's structural preconditions (driver/ingest.py): every
        (rung, bucket) pair is warmed at precompile, so mid-run bucket
        switches — the occupancy-collapse DROP and the recovery
        STEP-UP — are compile-cache hits; and a snapshot pull on the
        idle half of the double buffer (submit_backlog's overlap_work
        hook) adds no recompiles or implicit transfers.  The drains
        are double-buffered multi-group dispatches, so both halves of
        the ping/pong staging pair are exercised (the overlap counter
        proves staging ran while compute was in flight)."""
        s = 2
        eng = FleetFusedIngest(
            _params(), s, beams=BEAMS, buckets=(4, 8), max_revs=6,
            rungs=(1, 2),
        )
        assert eng.double_buffer
        eng.precompile([DENSE] * s)
        streams = [
            (DENSE, _make_stream(DENSE, 96, np.random.default_rng(40 + i),
                                 syncs=(0, 17, 34, 51, 68, 85)))
            for i in range(s)
        ]
        ticks = _mk_ticks(streams, np.random.default_rng(9), idle_prob=0.0)
        cut = max(4, len(ticks) // 3)
        eng.submit_backlog(ticks[:cut], rung=2)  # live-path warmup
        eng.snapshot_stream(0)  # warm the row-gather programs
        before = dict(eng.rung_bucket_dispatches)
        hits_before = eng.staging_overlap_hits
        snaps: list = []
        total = 0
        with guards.steady_state(tag="bucket switches + off-path snaps"):
            pos = cut
            # collapse to the small bucket, recover to the big one,
            # collapse again — every drain pulls a snapshot on the
            # idle half of the buffer
            for bucket, rung in ((4, 1), (4, 2), (8, 1), (8, 2), (4, 1)):
                if pos + 2 > len(ticks):
                    break
                eng.set_active_bucket(bucket)
                step = max(2 * rung, 2)
                outs = eng.submit_backlog(
                    ticks[pos : pos + step], rung=rung,
                    overlap_work=lambda: snaps.append(
                        eng.snapshot_stream(0)
                    ),
                )
                pos += step
                total += sum(len(o) for o in outs)
        assert eng.bucket_switches >= 2  # down AND back up applied
        assert eng.staging_overlap_hits > hits_before
        assert len(snaps) >= 3 and all(s_ is not None for s_ in snaps)
        assert total >= 1
        # the collapsed cap dispatched at the small bucket (the big cap
        # may legitimately also land there — _bucket() picks the
        # smallest covering bucket per slice), and the per-(rung,bucket)
        # accounting identity holds
        moved = {
            b for (r, b), n in eng.rung_bucket_dispatches.items()
            if n > before.get((r, b), 0)
        }
        assert 4 in moved
        assert (
            sum(eng.rung_bucket_dispatches.values()) == eng.dispatch_count
        )


class TestFleetMapperSteadyState:
    @pytest.mark.parametrize("match_backend", ["xla", "pallas"])
    def test_zero_recompiles_zero_implicit_transfers(self, match_backend):
        """Both matcher lowerings — the jnp arm and the Pallas kernels
        (interpret mode on this CPU backend, the exact code path a
        pallas-pinned CPU config runs) — hold the steady-state contract
        post-warmup: precompile() compiles every executable the live
        tick dispatches, including the in-program Pallas calls."""
        p = _params(
            map_enable=True, map_backend="fused", map_grid=64,
            map_cell_m=0.1, match_backend=match_backend,
        )
        b = 64
        m = FleetMapper(p, 2, beams=b)
        assert m.cfg.match_backend == match_backend
        m.precompile()
        rng = np.random.default_rng(3)

        def tick_args(seed):
            r = np.random.default_rng(seed)
            pts = r.uniform(-2.0, 2.0, (2, b, 2)).astype(np.float32)
            masks = np.ones((2, b), bool)
            live = np.ones((2,), np.int32)
            return pts, masks, live

        m.submit_points(*tick_args(0))  # warm the live path
        with guards.steady_state(tag="FleetMapper"):
            for k in range(1, 4):
                est = m.submit_points(*tick_args(k))
        assert m.dispatch_count == 4
        assert all(e is not None for e in est)
        del rng


# ---------------------------------------------------------------------------
# pod-of-pods: steals + a full autoscale cycle stay steady
# ---------------------------------------------------------------------------


class TestPodScaleoutSteadyState:
    def test_steal_and_scale_cycle_stay_in_the_compile_cache(self):
        """The pod-of-pods structural contract (ISSUE 17 acceptance):
        cross-shard steals are live row moves between ALREADY-COMPILED
        engines, a scale-down is a relabeling plus an engine release,
        and a scale-up re-admits the parked shard's warm executables —
        so a skew -> idle -> resume trace that forces steals AND a full
        down/up autoscale cycle runs with ZERO recompiles and ZERO
        implicit transfers after warmup, while every stream keeps
        publishing byte-identically to a static pod fed the same
        schedule (the steal/scale policies choose WHERE and WITH WHAT
        CAPACITY a queue drains, never what)."""
        from rplidar_ros2_driver_tpu.parallel.service import (
            ElasticFleetService,
        )

        from test_chaos import _fleet_ticks, _map_params

        streams, shards = 6, 3
        ticks = _fleet_ticks(streams, 24)

        def build(pod_arm):
            params = _map_params(
                fleet_ingest_backend="fused", map_backend="fused",
                shard_count=shards, failover_snapshot_ticks=4,
                shard_starvation_ticks=500,
                sched_rungs=(1, 2, 4),
                admission_max_backlog_ticks=16,
                steal_threshold_ticks=2 if pod_arm else 0,
                autoscale_enable=pod_arm,
                autoscale_low_watermark=0.3,
                autoscale_high_watermark=0.75,
                autoscale_hysteresis_ticks=3,
            )
            pod = ElasticFleetService(
                params, streams, shards=shards, beams=BEAMS,
                fleet_ingest_buckets=(8,),
            )
            pod.attach_scheduler()
            pod.precompile([DENSE])
            return pod

        pods = {"static": build(False), "pod": build(True)}
        deep = [
            s for s in pods["pod"].topology.lane_streams(0)
            if s is not None
        ][:2]
        cursor = [0] * streams

        def take(i, n):
            got = [
                ticks[t][i]
                for t in range(cursor[i], min(cursor[i] + n, len(ticks)))
            ]
            cursor[i] += len(got)
            return [g for g in got if g] or None

        wall = []
        for _ in range(6):    # skewed bursts -> steals
            wall.append([
                take(i, 4 if i in deep else 1) for i in range(streams)
            ])
        for _ in range(8):    # idle -> scale down (hysteresis 3)
            wall.append([None] * streams)
        for _ in range(14):   # full resume -> scale up + re-publish
            wall.append([take(i, 1) for i in range(streams)])

        outs = {n: [[] for _ in range(streams)] for n in pods}

        def run_tick(t, items):
            for name in (
                ("static", "pod") if t % 2 == 0 else ("pod", "static")
            ):
                pods[name].offer_bytes(items)
                for i, g in enumerate(pods[name].drain_scheduled()):
                    outs[name][i].extend(g)

        warm = 2
        for t in range(warm):
            run_tick(t, wall[t])
        with guards.steady_state(tag="pod steal + autoscale cycle"):
            for t in range(warm, len(wall)):
                run_tick(t, wall[t])

        pp = pods["pod"]
        assert pp.scheduler.steals > 0
        assert pp.steal_drops == 0
        assert pp.scheduler.steal_ticks == sum(
            n for *_, n in pp.scheduler.steal_log
        )
        downs = [e for e in pp.scale_events if e[1] == "down"]
        ups = [e for e in pp.scale_events if e[1] == "up"]
        assert downs and ups, f"no full scale cycle: {pp.scale_events}"
        assert pp.pod_status()["parked"] == []
        assert pods["static"].scheduler.steals == 0
        assert pods["static"].scale_events == []
        for i in range(streams):
            a, b = outs["pod"][i], outs["static"][i]
            assert len(a) == len(b) and len(a) > 0
            for x, y in zip(a, b):
                assert np.array_equal(
                    np.asarray(x.ranges), np.asarray(y.ranges)
                )
                assert np.array_equal(
                    np.asarray(x.voxel), np.asarray(y.voxel)
                )
