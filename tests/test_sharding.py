"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

Validates that the (stream, beam)-sharded pipeline produces bit-identical
results to the single-device fused filter_step — sharding must be a pure
layout decision, never a semantics change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rplidar_ros2_driver_tpu.driver.dummy import synth_scan
from rplidar_ros2_driver_tpu.ops.filters import FilterConfig, FilterState, filter_step
from rplidar_ros2_driver_tpu.parallel.sharding import (
    build_sharded_step,
    create_sharded_state,
    make_mesh,
    shard_batch,
)


def _make_batch(streams, count=64, capacity=128):
    return jax.vmap(lambda p: synth_scan(p, count=count, capacity=capacity))(
        jnp.linspace(0.0, 2.0, streams, dtype=jnp.float32)
    )


def test_mesh_factory_shapes():
    mesh = make_mesh(8)
    assert mesh.shape["stream"] * mesh.shape["beam"] == 8
    mesh2 = make_mesh(8, stream=4)
    assert mesh2.shape == {"stream": 4, "beam": 2}


def test_sharded_matches_single_device():
    mesh = make_mesh(8, stream=2)
    cfg = FilterConfig(window=4, beams=64, grid=16, cell_m=0.5)
    streams = 4

    step = build_sharded_step(mesh, cfg)
    state = create_sharded_state(mesh, cfg, streams)
    batch = _make_batch(streams)
    sbatch = shard_batch(mesh, batch)

    # three steps so the ring buffer wraps meaningfully
    for _ in range(3):
        state, out = step(state, sbatch)

    # single-device reference: vmap the fused step over streams
    ref_state = jax.vmap(lambda: FilterState.create(cfg.window, cfg.beams, cfg.grid),
                         axis_size=streams)()
    ref = jax.vmap(lambda s, b: filter_step(s, b, cfg))
    for _ in range(3):
        ref_state, ref_out = ref(ref_state, batch)

    np.testing.assert_array_equal(np.asarray(out.voxel), np.asarray(ref_out.voxel))
    np.testing.assert_allclose(
        np.asarray(out.ranges), np.asarray(ref_out.ranges), rtol=0, atol=0
    )
    np.testing.assert_array_equal(
        np.asarray(state.cursor), np.asarray(ref_state.cursor)
    )


def test_sharded_inc_median_matches_single_device():
    # the incremental sliding median is beam-local, so its sorted state
    # shards like the ring; outputs must stay bit-identical to the
    # single-device inc path AND (transitively) the sort path
    mesh = make_mesh(8, stream=2)
    cfg = FilterConfig(
        window=4, beams=64, grid=16, cell_m=0.5, median_backend="inc"
    )
    streams = 4

    step = build_sharded_step(mesh, cfg)
    state = create_sharded_state(mesh, cfg, streams)
    assert state.median_sorted is not None
    batch = _make_batch(streams)
    sbatch = shard_batch(mesh, batch)
    for _ in range(6):  # > one full wrap
        state, out = step(state, sbatch)

    ref_state = jax.vmap(
        lambda: FilterState.for_config(cfg), axis_size=streams
    )()
    ref = jax.vmap(lambda s, b: filter_step(s, b, cfg))
    for _ in range(6):
        ref_state, ref_out = ref(ref_state, batch)

    np.testing.assert_array_equal(np.asarray(out.ranges), np.asarray(ref_out.ranges))
    np.testing.assert_array_equal(np.asarray(out.voxel), np.asarray(ref_out.voxel))
    np.testing.assert_array_equal(
        np.asarray(state.median_sorted), np.asarray(ref_state.median_sorted)
    )


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_sharded_scan_matches_sharded_steps(backend):
    """build_sharded_scan (fused K-scan fleet replay) must reproduce the
    exact trajectory of K successive build_sharded_step calls — across a
    K < W chunk (surviving old window rows) and a K > W chunk (ring
    wrap), on both median backends."""
    from rplidar_ros2_driver_tpu.ops.filters import pack_host_scans_compact
    from rplidar_ros2_driver_tpu.parallel.sharding import build_sharded_scan

    mesh = make_mesh(8, stream=2)
    cfg = FilterConfig(window=4, beams=64, grid=16, cell_m=0.5,
                       median_backend=backend)
    streams, capacity = 4, 128
    rng = np.random.default_rng(7)
    per_stream = []
    for s in range(streams):
        scans = []
        for k in range(9):
            n = 50 + 3 * k + s
            scans.append({
                "angle_q14": ((np.arange(n) * 65536) // n).astype(np.int32),
                "dist_q2": (rng.uniform(0.3, 8.0, n) * 4000).astype(np.int32),
                "quality": np.full(n, 180, np.int32),
            })
        per_stream.append(scans)

    def batch_at(k):
        from rplidar_ros2_driver_tpu.ops.filters import pack_host_scan_compact

        bufs, counts = zip(*[
            pack_host_scan_compact(
                s[k]["angle_q14"], s[k]["dist_q2"], s[k]["quality"], None, capacity
            )
            for s in per_stream
        ])
        from rplidar_ros2_driver_tpu.ops.filters import _unpack_compact

        return jax.vmap(_unpack_compact)(
            jnp.asarray(np.stack(bufs)), jnp.asarray(counts, jnp.int32)
        )

    # reference: 9 sharded per-step calls
    step = build_sharded_step(mesh, cfg)
    s_ref = create_sharded_state(mesh, cfg, streams)
    ranges_ref = []
    for k in range(9):
        s_ref, out = step(s_ref, shard_batch(mesh, batch_at(k)))
        ranges_ref.append(np.asarray(out.ranges))

    # fused: K=3 (< W) then K=6 (> W) chunks
    scan_fn = build_sharded_scan(mesh, cfg)
    s_fused = create_sharded_state(mesh, cfg, streams)
    got = []
    for lo, hi in ((0, 3), (3, 9)):
        seqs, counts = zip(*[
            pack_host_scans_compact(s[lo:hi], capacity) for s in per_stream
        ])
        s_fused, ranges = scan_fn(
            s_fused, jnp.asarray(np.stack(seqs)), jnp.asarray(np.stack(counts))
        )
        got.append(np.asarray(ranges))
    got = np.concatenate(got, axis=1)  # (streams, 9, beams)

    np.testing.assert_array_equal(
        got.transpose(1, 0, 2), np.stack(ranges_ref)
    )
    for name in ("range_window", "inten_window", "hit_window", "voxel_acc",
                 "cursor", "filled"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_fused, name)), np.asarray(getattr(s_ref, name)), name
        )


def test_ring_reduce_matches_psum():
    """The explicit ppermute ring all-reduce is semantically psum: the
    sharded step under voxel_reduce='ring' must be bit-identical to the
    default, across a beam axis wide enough for multiple hops."""
    mesh = make_mesh(8, stream=2)  # beam axis = 4 -> 3 ring hops
    streams = 2
    batch = _make_batch(streams)
    outs = {}
    for mode in ("psum", "ring"):
        cfg = FilterConfig(window=4, beams=64, grid=16, cell_m=0.5, voxel_reduce=mode)
        step = build_sharded_step(mesh, cfg)
        state = create_sharded_state(mesh, cfg, streams)
        sbatch = shard_batch(mesh, batch)
        for _ in range(3):
            state, out = step(state, sbatch)
        outs[mode] = (np.asarray(out.voxel), np.asarray(state.voxel_acc))
    np.testing.assert_array_equal(outs["ring"][0], outs["psum"][0])
    np.testing.assert_array_equal(outs["ring"][1], outs["psum"][1])


@pytest.mark.parametrize("n", [2, 4, 8])
def test_dryrun_multichip(n):
    import __graft_entry__ as ge

    ge.dryrun_multichip(n)


def test_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
