"""Repeated hot-unplug recovery — the flagship feature, cycled.

The reference's community protocol is one manual cable pull
(README.md:27-38); single-unplug recovery is covered in
test_real_driver.py / test_fleet_integration.py.  This cycles it: the
node must survive SEVERAL unplug->reconnect rounds in one session, each
time re-detecting the device, re-selecting the scan mode, and resuming
publishing — no cumulative state corruption across driver recreations.
"""

import time

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
from rplidar_ros2_driver_tpu.node.fsm import FsmTimings
from rplidar_ros2_driver_tpu.node.node import RPlidarNode

CYCLES = 3


def test_repeated_unplug_recovery():
    sim = SimulatedDevice().start()
    node = None
    try:
        params = DriverParams(
            dummy_mode=False, channel_type="tcp", scan_mode="DenseBoost",
            filter_backend="cpu", filter_chain=("clip", "median"),
            filter_window=4, max_retries=2,
        )
        node = RPlidarNode(
            params,
            driver_factory=lambda: RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0),
            fsm_timings=FsmTimings(
                connect_retry_s=0.1, reset_backoff_s=0.2, idle_tick_s=0.01,
                grab_retry_s=0.01,
            ),
        )
        assert node.configure()
        assert node.activate()

        from conftest import wait_for

        def wait_streaming(n, timeout=25.0):
            base = node.publisher.scan_count
            assert wait_for(
                lambda: node.publisher.scan_count >= base + n, timeout
            ), "stream did not resume"

        wait_streaming(3)
        for cycle in range(1, CYCLES + 1):
            resets_before = node.fsm.reset_count
            sim.unplug()
            assert wait_for(
                lambda: node.fsm.reset_count > resets_before, 30
            ), f"cycle {cycle}: no reset"
            wait_streaming(3)  # recovered and publishing again
            assert node.fsm.driver.profile.active_mode == "DenseBoost"
        assert node.fsm.reset_count >= CYCLES
    finally:
        if node is not None:
            node.shutdown()
        sim.stop()
