"""Fleet-gateway integration: N concurrent SimulatedDevices end-to-end.

The multi-stream story beyond unit tests (VERDICT r1 #8): each stream is a
full production stack — protocol simulator → native TCP channel → batched
decode (driver/decode.py) → assembler → fault-tolerant ScanLoopFsm — and
the newest revolution of every stream feeds one ShardedFilterService tick
on the virtual 8-device (stream, beam) mesh.  Also exercises one stream's
hot-unplug mid-run: the fleet keeps ticking (idle stream = all-masked
scan), the dead stream's FSM goes into recovery, and service output
resumes for the healthy streams.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
from rplidar_ros2_driver_tpu.node.fsm import DriverState, FsmTimings, ScanLoopFsm
from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh

N_STREAMS = 4


from conftest import wait_for


def _wait(cond, timeout=20.0, dt=0.02):
    return wait_for(cond, timeout, dt)


class _Stream:
    """One lidar stream: sim device + driver + FSM + newest-scan mailbox."""

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.sim = SimulatedDevice().start()
        self.lock = threading.Lock()
        self.newest: dict | None = None
        self.scan_count = 0
        params = DriverParams(
            serial_port=f"sim{idx}",
            serial_baudrate=0,
            scan_mode="DenseBoost",
            max_retries=2,
        )
        self.fsm = ScanLoopFsm(
            self._make_driver,
            self._on_scan,
            params=params,
            timings=FsmTimings.fast(),
        )

    def _make_driver(self) -> RealLidarDriver:
        return RealLidarDriver(
            channel_type="tcp",
            tcp_host="127.0.0.1",
            tcp_port=self.sim.port,
            motor_warmup_s=0.0,
        )

    def _on_scan(self, scan: dict, ts0: float, duration: float) -> None:
        with self.lock:
            self.newest = scan
            self.scan_count += 1

    def take(self) -> dict | None:
        with self.lock:
            scan, self.newest = self.newest, None
        return scan

    def stop(self) -> None:
        self.fsm.stop()
        self.sim.stop()


def test_fleet_of_sims_through_sharded_service():
    mesh = make_mesh(8)
    assert mesh.shape["stream"] * mesh.shape["beam"] == 8
    params = DriverParams(
        dummy_mode=True,
        filter_backend="cpu",
        filter_chain=("clip", "median", "voxel"),
        filter_window=4,
        voxel_grid_size=32,
    )
    svc = ShardedFilterService(params, N_STREAMS, mesh=mesh, beams=512)
    streams = [_Stream(i) for i in range(N_STREAMS)]
    try:
        for s in streams:
            s.fsm.start()
        # all four independent stacks must reach RUNNING and produce scans
        assert _wait(lambda: all(s.scan_count >= 2 for s in streams)), [
            (s.fsm.state, s.scan_count) for s in streams
        ]

        # tick the fleet: every stream's newest revolution in one dispatch
        ticks_with_all = 0
        outputs = None
        for _ in range(30):
            scans = [s.take() for s in streams]
            outputs = svc.submit(scans)
            if all(sc is not None for sc in scans):
                ticks_with_all += 1
                for i, out in enumerate(outputs):
                    assert out is not None
                    assert out.ranges.shape == (svc.cfg.beams,)
                    assert np.isfinite(out.ranges).any(), f"stream {i} all-inf"
                    assert out.voxel.shape == (svc.cfg.grid, svc.cfg.grid)
            if ticks_with_all >= 3:
                break
            time.sleep(0.05)
        assert ticks_with_all >= 3

        # hot-unplug stream 0 mid-run: its FSM must leave RUNNING and the
        # fleet must keep producing output for the healthy streams
        streams[0].sim.unplug()
        assert _wait(
            lambda: streams[0].fsm.state is not DriverState.RUNNING, timeout=30.0
        ), streams[0].fsm.state

        healthy_seen = 0
        for _ in range(30):
            scans = [s.take() for s in streams]
            outputs = svc.submit(scans)
            got = [o is not None for o in outputs[1:]]
            if all(sc is not None for sc in scans[1:]):
                healthy_seen += 1
                for out in outputs[1:]:
                    assert np.isfinite(out.ranges).any()
            if healthy_seen >= 2:
                break
            time.sleep(0.05)
        assert healthy_seen >= 2, "healthy streams stopped producing after unplug"
    finally:
        for s in streams:
            s.stop()
