"""Legacy compat shim tests (rplidar_driver.cpp facade + RPLIDAR_* aliases)."""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu import compat
from rplidar_ros2_driver_tpu.driver.dummy import DummyLidarDriver
from rplidar_ros2_driver_tpu.core.results import DeviceHealth
from rplidar_ros2_driver_tpu.protocol import constants as c


def test_alias_values_match_modern_enums():
    # spot checks mirroring rplidar_cmd.h:42-70
    assert compat.RPLIDAR_CMD_STOP == 0x25
    assert compat.RPLIDAR_CMD_SCAN == 0x20
    assert compat.RPLIDAR_CMD_FORCE_SCAN == 0x21
    assert compat.RPLIDAR_CMD_RESET == 0x40
    assert compat.RPLIDAR_CMD_EXPRESS_SCAN == 0x82
    assert compat.RPLIDAR_CMD_SET_MOTOR_PWM == 0xF0
    assert compat.RPLIDAR_ANS_TYPE_MEASUREMENT == int(c.Ans.MEASUREMENT)
    assert compat.RPLIDAR_ANS_TYPE_DEVINFO == 0x04
    assert compat.RPLIDAR_STATUS_OK == 0
    assert compat.RPLIDAR_STATUS_ERROR == 2
    assert compat.RPLIDAR_CMD_SYNC_BYTE == 0xA5
    assert compat.MAX_SCAN_NODES == 8192


def test_facade_forwards_to_impl():
    drv = compat.RPlidarDriver(DummyLidarDriver())
    assert drv.connect("/dev/fake", 115200)
    assert drv.isConnected()
    assert drv.getHealth() == DeviceHealth.OK
    assert drv.startScan()
    batch = drv.grabScanDataHq(2000)
    assert batch is not None
    host = batch.to_host()
    assert host["angle_q14"].shape[0] > 0
    asc = drv.ascendScanData(batch)
    ang = np.asarray(asc.angle_q14)[: int(asc.count)]
    assert (np.diff(ang.astype(np.int64)) >= 0).all()
    drv.stop()
    drv.stopMotor()
    compat.RPlidarDriver.DisposeDriver(drv)  # dummy stays "connected" by design


def test_create_driver_warns_deprecated():
    with pytest.warns(DeprecationWarning):
        compat.RPlidarDriver.CreateDriver(impl=DummyLidarDriver())


def test_unsupported_legacy_args_warn():
    drv = compat.RPlidarDriver(DummyLidarDriver())
    with pytest.warns(RuntimeWarning, match="FORCE_SCAN"):
        drv.startScan(force=True)
    with pytest.warns(RuntimeWarning, match="fixed_angle"):
        drv.startScanExpress(True, "Standard")


def test_force_scan_against_sim(tmp_path):
    """startScan(force=True) sends FORCE_SCAN 0x21 and streams."""
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice

    sim = SimulatedDevice().start()
    try:
        drv = RealLidarDriver(
            channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
            motor_warmup_s=0.0, legacy_warmup_s=0.0,
        )
        facade = compat.RPlidarDriver(drv)
        assert facade.connect("sim", 0)
        assert facade.startScan(force=True)  # no warning on real backend
        batch = facade.grabScanDataHq(5000)
        assert batch is not None and int(batch.count) > 0
        assert drv.profile.active_mode == "Standard (forced)"
        facade.stop()
        facade.disconnect()
    finally:
        sim.stop()


def test_profile_trace_smoke(tmp_path):
    import jax.numpy as jnp

    from rplidar_ros2_driver_tpu.utils.tracing import profile_trace

    with profile_trace(str(tmp_path)):
        jnp.ones((8, 8)).sum().block_until_ready()
    import os

    assert any(os.scandir(str(tmp_path)))  # trace files written
