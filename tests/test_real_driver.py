"""RealLidarDriver against the protocol-accurate SimulatedDevice over TCP:
the full stack — native channel + transceiver -> codec -> command engine ->
conf protocol -> per-format decode -> scan assembly — without hardware.
Also drives the whole node FSM over it, including automated hot-unplug."""

import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu import native as native_mod
from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.core.results import DeviceHealth
from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import SimConfig, SimulatedDevice

pytestmark = pytest.mark.skipif(
    not native_mod.available(), reason="native library unavailable"
)


from conftest import wait_for


def _wait(predicate, timeout=10.0, interval=0.01):
    return wait_for(predicate, timeout, interval)


def make_driver(sim: SimulatedDevice, **kw) -> RealLidarDriver:
    return RealLidarDriver(
        channel_type="tcp",
        tcp_host=SimulatedDevice.TARGET,
        tcp_port=sim.port,
        motor_warmup_s=0.0,
        legacy_warmup_s=0.0,
        **kw,
    )


@pytest.fixture
def sim():
    dev = SimulatedDevice().start()
    yield dev
    dev.stop()


class TestConnect:
    def test_connect_and_identify(self, sim):
        drv = make_driver(sim)
        assert drv.connect("ignored", 0, True)
        assert drv.is_connected()
        info = drv.device_info
        assert info.model == 0x71
        assert "S7M1" not in info.summary()
        drv.detect_and_init_strategy()
        assert drv.is_new_type()
        assert drv.get_hw_max_distance() == 40.0
        drv.disconnect()
        assert not drv.is_connected()

    def test_connect_failure_no_server(self):
        drv = RealLidarDriver(channel_type="tcp", tcp_host="127.0.0.1", tcp_port=1)
        assert not drv.connect("ignored", 0, True)

    def test_health(self, sim):
        drv = make_driver(sim)
        assert drv.connect("ignored", 0, True)
        assert drv.get_health() is DeviceHealth.OK
        sim.cfg.health_status = 2
        assert drv.get_health() is DeviceHealth.ERROR
        drv.disconnect()

    def test_legacy_model_profile(self):
        dev = SimulatedDevice(SimConfig(model_id=0x18)).start()  # A1M8
        try:
            drv = make_driver(dev)
            assert drv.connect("ignored", 0, True)
            drv.detect_and_init_strategy()
            assert not drv.is_new_type()
            assert drv.get_hw_max_distance() == 12.0
            drv.print_summary()  # smoke: the SDK summary table renders
            drv.disconnect()
        finally:
            dev.stop()

    def test_legacy_samplerate_queried_from_device(self):
        """OLD_TYPE startup on firmware >= 1.17 must ask the device for its
        sample duration (GET_SAMPLERATE, sl_lidar_driver.cpp:1556-1599)
        instead of assuming the 476 us legacy default.  Pre-conf firmware
        takes the Express fallback, so the EXPRESS duration is the one
        that lands in the timing model (startScanExpress legacy branch,
        :722)."""
        from rplidar_ros2_driver_tpu.protocol.constants import Cmd

        # firmware exactly 1.17 (0x0111): the boundary itself must query —
        # pins the `< 1.17` comparison direction in real.py
        dev = SimulatedDevice(SimConfig(
            model_id=0x18, firmware=0x0111,
            std_sample_us=500, express_sample_us=250,
        )).start()
        try:
            drv = make_driver(dev)
            assert drv.connect("ignored", 0, True)
            drv.detect_and_init_strategy()
            assert drv.start_motor("", 600)
            assert Cmd.GET_SAMPLERATE in dev.commands
            assert drv._scan_decoder.timing.sample_duration_us == 250.0
            drv.stop_motor()
            drv.disconnect()
        finally:
            dev.stop()

    def test_legacy_samplerate_default_on_old_firmware(self):
        """Firmware < 1.17 predates GET_SAMPLERATE: the command must not be
        sent and timing falls back to the 476 us table value."""
        from rplidar_ros2_driver_tpu.protocol.constants import Cmd
        from rplidar_ros2_driver_tpu.protocol.timing import LEGACY_SAMPLE_DURATION_US

        dev = SimulatedDevice(SimConfig(
            model_id=0x18, firmware=0x0105, std_sample_us=500,
        )).start()
        try:
            drv = make_driver(dev)
            assert drv.connect("ignored", 0, True)
            drv.detect_and_init_strategy()
            assert drv.start_motor("", 600)
            assert Cmd.GET_SAMPLERATE not in dev.commands
            assert drv._scan_decoder.timing.sample_duration_us == (
                LEGACY_SAMPLE_DURATION_US
            )
            drv.stop_motor()
            drv.disconnect()
        finally:
            dev.stop()


class TestScanStreaming:
    def _grab_scans(self, drv, n=2, timeout=3.0):
        scans = []
        deadline = time.monotonic() + 10
        while len(scans) < n and time.monotonic() < deadline:
            b = drv.grab_scan_data(timeout)
            if b is not None:
                scans.append(b)
        return scans

    def test_denseboost_auto_selection_and_scan(self, sim):
        drv = make_driver(sim)
        assert drv.connect("ignored", 0, False)
        drv.detect_and_init_strategy()
        assert drv.start_motor("", 720)
        assert drv.profile.active_mode == "DenseBoost"
        assert sim.motor_rpm == 720
        scans = self._grab_scans(drv, 2)
        assert len(scans) == 2
        batch = scans[-1]
        count = int(batch.count)
        # 400 points per simulated revolution (exact: frame-aligned assembly)
        assert 320 <= count <= 440
        dist_m = np.asarray(batch.dist_q2)[:count] / 4000.0
        assert dist_m.min() > 1.2 and dist_m.max() < 2.8  # 2m +/- 0.5m scene
        drv.stop_motor()
        drv.disconnect()

    def test_user_mode_preference(self, sim):
        drv = make_driver(sim)
        assert drv.connect("ignored", 0, False)
        drv.detect_and_init_strategy()
        assert drv.start_motor("Sensitivity", 0)
        assert drv.profile.active_mode == "Sensitivity"
        scans = self._grab_scans(drv, 1)
        assert scans and int(scans[0].count) > 100
        drv.stop_motor()
        drv.disconnect()

    def test_unknown_mode_falls_back(self, sim):
        drv = make_driver(sim)
        assert drv.connect("ignored", 0, False)
        drv.detect_and_init_strategy()
        assert drv.start_motor("NoSuchMode", 0)
        assert drv.profile.active_mode == "DenseBoost"
        drv.stop_motor()
        drv.disconnect()

    def test_legacy_scan_path(self):
        """A pre-conf A1M8 starts via the typical-mode EXPRESS fallback:
        capsule stream, working_mode 0 on the wire, zero conf queries
        (the reference wrapper's startScan(0, 1) through getTypicalScanMode
        sl_lidar_driver.cpp:577-580)."""
        from rplidar_ros2_driver_tpu.protocol.constants import Ans, Cmd

        dev = SimulatedDevice(SimConfig(model_id=0x18, points_per_rev=80)).start()
        try:
            drv = make_driver(dev)
            assert drv.connect("ignored", 0, False)
            drv.detect_and_init_strategy()
            assert not drv.conf_supported
            assert drv.start_motor("", 0)
            assert drv.profile.active_mode == "Express"
            # start_motor is fire-and-forget on the wire (send_only, like
            # the reference): poll until the sim's rx thread has observed
            # the EXPRESS_SCAN rather than racing it (load-flaky otherwise)
            assert wait_for(
                lambda: dev.active_ans_type == Ans.MEASUREMENT_CAPSULED, 10.0
            ), dev.active_ans_type
            # the wrapper profile keeps the A-series 12 m limit; 16 m is
            # SDK mode metadata only
            assert drv.get_hw_max_distance() == 12.0
            scans = self._grab_scans(drv, 1)
            assert scans and 40 <= int(scans[0].count) <= 90
            assert Cmd.GET_LIDAR_CONF not in dev.commands
            drv.stop_motor()
            drv.disconnect()
        finally:
            dev.stop()

    def test_conf_capable_old_triangle_uses_typical_mode(self):
        """An A-series unit with firmware >= 1.24 speaks the conf protocol:
        OLD_TYPE startup resolves the typical mode via conf and starts the
        express stream for it (startScan(0,1) -> getTypicalScanMode conf
        branch, sl_lidar_driver.cpp:562-575)."""
        from rplidar_ros2_driver_tpu.protocol.constants import Cmd

        dev = SimulatedDevice(SimConfig(
            model_id=0x18, firmware=(0x1 << 8) | 24,
        )).start()
        try:
            drv = make_driver(dev)
            assert drv.connect("ignored", 0, True)
            drv.detect_and_init_strategy()
            assert not drv.is_new_type()
            assert drv.conf_supported
            assert drv.start_motor("", 0)
            # the sim's typical mode is DenseBoost
            assert drv.profile.active_mode == "DenseBoost"
            assert Cmd.GET_LIDAR_CONF in dev.commands
            drv.stop_motor()
            drv.disconnect()
        finally:
            dev.stop()

    def test_angle_compensation_sorts_angles(self, sim):
        drv = make_driver(sim)
        assert drv.connect("ignored", 0, True)  # compensation on
        drv.detect_and_init_strategy()
        assert drv.start_motor("", 0)
        scans = self._grab_scans(drv, 2)
        assert scans
        b = scans[-1]
        c = int(b.count)
        ang = np.asarray(b.angle_q14)[:c]
        # ascend_scan interpolates invalid + returns monotone-ish angles
        assert (np.diff(ang.astype(np.int64)) >= 0).mean() > 0.95
        drv.stop_motor()
        drv.disconnect()


class TestHotUnplug:
    def test_unplug_detected_and_grab_fails(self, sim):
        drv = make_driver(sim)
        assert drv.connect("ignored", 0, False)
        drv.detect_and_init_strategy()
        assert drv.start_motor("", 0)
        assert drv.grab_scan_data(3.0) is not None
        sim.unplug()
        assert _wait(lambda: not drv.is_connected(), timeout=5.0)
        assert drv.grab_scan_data(0.3) is None
        drv.disconnect()

    def test_fsm_recovers_after_unplug(self, sim):
        """Full node stack over the simulated device: hot-unplug mid-scan,
        FSM resets, reconnects to the (re-listening) device, scans resume —
        the automated version of the reference's unplug protocol."""
        from rplidar_ros2_driver_tpu.node.fsm import FsmTimings
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode, launch
        from rplidar_ros2_driver_tpu.node.publisher import CollectingPublisher

        params = DriverParams(channel_type="tcp", max_retries=2)
        pub = CollectingPublisher()
        node = RPlidarNode(
            params,
            pub,
            driver_factory=lambda: make_driver(sim),
            fsm_timings=FsmTimings.fast(),
        )
        launch(node)
        assert _wait(lambda: pub.scan_count >= 2, timeout=10.0)
        sim.unplug()
        assert _wait(lambda: node.fsm.reset_count >= 1, timeout=10.0)
        before = pub.scan_count
        assert _wait(lambda: pub.scan_count > before + 1, timeout=10.0)
        node.shutdown()


class TestSerialTransportE2E:
    """Full protocol over a pty: the driver's SERIAL channel (termios2)
    against the emulator — devinfo, mode start, streaming, hot-unplug."""

    def test_serial_connect_stream_unplug(self):
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import SerialSimulatedDevice

        sim = SerialSimulatedDevice().start()
        try:
            drv = RealLidarDriver(channel_type="serial", motor_warmup_s=0.0)
            assert drv.connect(sim.port_path, 115200, True)
            drv.detect_and_init_strategy()
            assert drv.start_motor("", 600)
            got = None
            deadline = time.monotonic() + 15
            while got is None and time.monotonic() < deadline:
                got = drv.grab_scan_host(2.0)
            assert got is not None
            scan, ts0, dur = got
            assert len(scan["angle_q14"]) > 0
            assert dur > 0
            # serial link: timing desc carries the device model's NATIVE
            # baud for back-dating (sl_lidar_driver.cpp:1540 — not the
            # negotiated link baud); the sim's S2 model id maps to 1 Mbaud
            assert drv._scan_decoder.timing.is_serial
            assert drv._scan_decoder.timing.native_baudrate == 1_000_000
            sim.unplug()  # EIO on the slave, like a yanked USB adapter
            t0 = time.monotonic()
            while drv.grab_scan_host(0.5) is not None:
                assert time.monotonic() - t0 < 10
            drv.disconnect()
        finally:
            sim.stop()


class TestUdpTransportE2E:
    """Full protocol over UDP datagrams through the native UDP channel."""

    def test_udp_connect_stream_silence(self):
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import UdpSimulatedDevice

        sim = UdpSimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="udp", udp_host="127.0.0.1", udp_port=sim.port,
                motor_warmup_s=0.0,
            )
            assert drv.connect("udp", 0, True)
            drv.detect_and_init_strategy()
            assert drv.start_motor("", 600)
            got = None
            deadline = time.monotonic() + 15
            while got is None and time.monotonic() < deadline:
                got = drv.grab_scan_host(2.0)
            assert got is not None
            assert len(got[0]["angle_q14"]) > 0
            assert not drv._scan_decoder.timing.is_serial
            sim.unplug()  # radio dies: silence, grabs must time out
            t0 = time.monotonic()
            while drv.grab_scan_host(0.5) is not None:
                assert time.monotonic() - t0 < 10
            drv.disconnect()
        finally:
            sim.stop()
