"""Race-surface smoke test.

The reference's known latent hazards (SURVEY.md §5): parameters_callback
can touch the driver while CONNECTING/WARMUP run unlocked, and decoder
state is process-global.  This framework claims both are fixed (FSM holds
the driver mutex in every state; per-decoder state).  This test exercises
the claim the way a sanitizer would: while the node streams from the
protocol simulator, several threads hammer dynamic reconfigure,
diagnostics, and checkpoint snapshots concurrently for a few seconds —
any exception, deadlock, or stall fails the test.
"""

import threading
import time

import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
from rplidar_ros2_driver_tpu.node.node import RPlidarNode


@pytest.mark.parametrize("pipelined", [False, True])
def test_reconfigure_diagnostics_checkpoint_under_streaming(tmp_path, pipelined):
    # pipelined=True additionally races the checkpoint/restore epoch
    # guard against the pending-output slot (the round-3/4 seam)
    sim = SimulatedDevice().start()
    node = None
    errors: list[BaseException] = []
    stop = threading.Event()
    try:
        params = DriverParams(
            dummy_mode=False, channel_type="tcp",
            filter_backend="cpu", filter_window=4,
            filter_chain=("clip", "median", "voxel"), voxel_grid_size=32,
            pipelined_publish=pipelined,
        )
        node = RPlidarNode(params, driver_factory=lambda: RealLidarDriver(
            channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
            motor_warmup_s=0.0))
        assert node.configure()
        assert node.activate()
        deadline = time.monotonic() + 20
        while node.publisher.scan_count < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert node.publisher.scan_count >= 2

        def guarded(fn):
            def loop():
                k = 0
                while not stop.is_set():
                    try:
                        fn(k)
                    except BaseException as e:  # noqa: BLE001 - the test IS the catch-all
                        errors.append(e)
                        return
                    k += 1
                    time.sleep(0.002)
            return loop

        ckpt = str(tmp_path / "race.npz")
        threads = [
            threading.Thread(target=guarded(
                lambda k: node.set_parameters({"rpm": 600 + (k % 5) * 60}))),
            threading.Thread(target=guarded(
                lambda k: node.set_parameters({"scan_processing": bool(k % 2)}))),
            threading.Thread(target=guarded(lambda k: node._update_diagnostics())),
            threading.Thread(target=guarded(lambda k: node.save_checkpoint(ckpt))),
        ]
        for t in threads:
            t.start()
        base = node.publisher.scan_count
        time.sleep(5.0)
        stop.set()
        for t in threads:
            t.join(5.0)
            assert not t.is_alive(), "worker deadlocked"
        assert not errors, errors
        # streaming survived the hammering
        assert node.publisher.scan_count > base
        assert node.fsm.reset_count == 0
        # the last dynamic rpm actually reached the device
        assert sim.motor_rpm in range(600, 900)
    finally:
        stop.set()
        if node is not None:
            node.shutdown()
        sim.stop()


def test_service_snapshot_races_submit():
    """Sharded-service analog of the chain race: snapshots hammered from
    another thread while ticks stream must never observe donated-deleted
    buffers."""
    from test_sharded_service import _params, _scan  # shared fixtures

    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService

    svc = ShardedFilterService(_params(), streams=2, beams=128, capacity=512)
    scan = _scan

    stop = threading.Event()
    errors: list[BaseException] = []

    def snapshotter():
        while not stop.is_set():
            try:
                snap = svc.snapshot()
                assert snap["voxel_acc"].shape == (2, 32, 32)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return

    t = threading.Thread(target=snapshotter)
    t.start()
    try:
        for k in range(200):
            svc.submit([scan(k), scan(k + 1000)])
    finally:
        stop.set()
        t.join(5.0)
    assert not t.is_alive()
    assert not errors, errors


def test_service_pipelined_ticks_race_restore():
    """Pipelined ticks hammered while another thread restores: every
    interleaving must be exception- and deadlock-free, and the service
    must still stream correctly once the hammering stops.  (The
    deterministic drop-don't-republish statement of the epoch guard is
    test_sharded_service.py::test_submit_pipelined_restore_drops_next_
    tick_output; under racing, output values are interleaving-dependent,
    so this test's teeth are crashes, hangs, and post-race liveness.)"""
    from test_sharded_service import _params, _scan  # shared fixtures

    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService

    svc = ShardedFilterService(_params(), streams=2, beams=128, capacity=512)
    stop = threading.Event()
    errors: list[BaseException] = []

    def restorer():
        while not stop.is_set():
            try:
                svc.restore(None)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return
            time.sleep(0.003)

    t = threading.Thread(target=restorer)
    t.start()
    try:
        for k in range(200):
            outs = svc.submit_pipelined([_scan(k), _scan(k + 1000)])
            assert len(outs) == 2
    finally:
        stop.set()
        t.join(5.0)
    assert not t.is_alive()
    assert not errors, errors
    svc.flush_pipelined()  # drain must also survive post-hammering
    # post-race liveness: with the restorer stopped, the pipelined
    # stream works normally again (tick N returns tick N-1's output)
    svc.restore(None)
    assert svc.submit_pipelined([_scan(1), _scan(2)]) == [None, None]
    out = svc.submit_pipelined([_scan(3), _scan(4)])
    assert out[0] is not None and out[0].ranges.shape == (128,)


def test_two_nodes_two_devices_are_isolated():
    """Per-instance decoder state (vs the reference's process-global
    `static lastNodeSyncBit`): two concurrent driver stacks must not
    perturb each other's streams."""
    sims = [SimulatedDevice().start() for _ in range(2)]
    drvs = []
    try:
        for sim in sims:
            d = RealLidarDriver(channel_type="tcp", tcp_host="127.0.0.1",
                                tcp_port=sim.port, motor_warmup_s=0.0)
            assert d.connect("sim", 0, False)
            d.detect_and_init_strategy()
            assert d.start_motor("DenseBoost", 600)
            drvs.append(d)
        counts = [0, 0]
        deadline = time.monotonic() + 20
        while min(counts) < 3 and time.monotonic() < deadline:
            for i, d in enumerate(drvs):
                got = d.grab_scan_host(0.5)
                if got is not None:
                    scan, _, dur = got
                    assert len(scan["angle_q14"]) > 100
                    assert dur > 0  # early revolutions may be partial
                    counts[i] += 1
        assert min(counts) >= 3, counts
    finally:
        for d in drvs:
            d.stop_motor()
            d.disconnect()
        for s in sims:
            s.stop()
