"""Race-surface smoke test.

The reference's known latent hazards (SURVEY.md §5): parameters_callback
can touch the driver while CONNECTING/WARMUP run unlocked, and decoder
state is process-global.  This framework claims both are fixed (FSM holds
the driver mutex in every state; per-decoder state).  This test exercises
the claim the way a sanitizer would: while the node streams from the
protocol simulator, several threads hammer dynamic reconfigure,
diagnostics, and checkpoint snapshots concurrently for a few seconds —
any exception, deadlock, or stall fails the test.
"""

import threading
import time


from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
from rplidar_ros2_driver_tpu.node.node import RPlidarNode


def test_reconfigure_diagnostics_checkpoint_under_streaming(tmp_path):
    sim = SimulatedDevice().start()
    node = None
    errors: list[BaseException] = []
    stop = threading.Event()
    try:
        params = DriverParams(
            dummy_mode=False, channel_type="tcp",
            filter_backend="cpu", filter_window=4,
            filter_chain=("clip", "median", "voxel"), voxel_grid_size=32,
        )
        node = RPlidarNode(params, driver_factory=lambda: RealLidarDriver(
            channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
            motor_warmup_s=0.0))
        assert node.configure()
        assert node.activate()
        deadline = time.monotonic() + 20
        while node.publisher.scan_count < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert node.publisher.scan_count >= 2

        def guarded(fn):
            def loop():
                k = 0
                while not stop.is_set():
                    try:
                        fn(k)
                    except BaseException as e:  # noqa: BLE001 - the test IS the catch-all
                        errors.append(e)
                        return
                    k += 1
                    time.sleep(0.002)
            return loop

        ckpt = str(tmp_path / "race.npz")
        threads = [
            threading.Thread(target=guarded(
                lambda k: node.set_parameters({"rpm": 600 + (k % 5) * 60}))),
            threading.Thread(target=guarded(
                lambda k: node.set_parameters({"scan_processing": bool(k % 2)}))),
            threading.Thread(target=guarded(lambda k: node._update_diagnostics())),
            threading.Thread(target=guarded(lambda k: node.save_checkpoint(ckpt))),
        ]
        for t in threads:
            t.start()
        base = node.publisher.scan_count
        time.sleep(5.0)
        stop.set()
        for t in threads:
            t.join(5.0)
            assert not t.is_alive(), "worker deadlocked"
        assert not errors, errors
        # streaming survived the hammering
        assert node.publisher.scan_count > base
        assert node.fsm.reset_count == 0
        # the last dynamic rpm actually reached the device
        assert sim.motor_rpm in range(600, 900)
    finally:
        stop.set()
        if node is not None:
            node.shutdown()
        sim.stop()


def test_service_snapshot_races_submit():
    """Sharded-service analog of the chain race: snapshots hammered from
    another thread while ticks stream must never observe donated-deleted
    buffers."""
    from test_sharded_service import _params, _scan  # shared fixtures

    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService

    svc = ShardedFilterService(_params(), streams=2, beams=128, capacity=512)
    scan = _scan

    stop = threading.Event()
    errors: list[BaseException] = []

    def snapshotter():
        while not stop.is_set():
            try:
                snap = svc.snapshot()
                assert snap["voxel_acc"].shape == (2, 32, 32)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return

    t = threading.Thread(target=snapshotter)
    t.start()
    try:
        for k in range(200):
            svc.submit([scan(k), scan(k + 1000)])
    finally:
        stop.set()
        t.join(5.0)
    assert not t.is_alive()
    assert not errors, errors


def test_two_nodes_two_devices_are_isolated():
    """Per-instance decoder state (vs the reference's process-global
    `static lastNodeSyncBit`): two concurrent driver stacks must not
    perturb each other's streams."""
    sims = [SimulatedDevice().start() for _ in range(2)]
    drvs = []
    try:
        for sim in sims:
            d = RealLidarDriver(channel_type="tcp", tcp_host="127.0.0.1",
                                tcp_port=sim.port, motor_warmup_s=0.0)
            assert d.connect("sim", 0, False)
            d.detect_and_init_strategy()
            assert d.start_motor("DenseBoost", 600)
            drvs.append(d)
        counts = [0, 0]
        deadline = time.monotonic() + 20
        while min(counts) < 3 and time.monotonic() < deadline:
            for i, d in enumerate(drvs):
                got = d.grab_scan_host(0.5)
                if got is not None:
                    scan, _, dur = got
                    assert len(scan["angle_q14"]) > 100
                    assert dur > 0  # early revolutions may be partial
                    counts[i] += 1
        assert min(counts) >= 3, counts
    finally:
        for d in drvs:
            d.stop_motor()
            d.disconnect()
        for s in sims:
            s.stop()
