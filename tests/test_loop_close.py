"""SLAM back-end suite (slam/loop + ops/loop_close + ops/pose_graph).

The contracts under test:

  * SOLVER GOLDEN — a known loop with injected drift relaxes to lattice
    resolution (the fixed-point Gauss–Newton relaxation actually
    closes loops, not just compiles).
  * PARITY — the jitted single-stream and vmapped fleet lowerings are
    BIT-EXACT against the NumPy ``_ref`` twins over randomized
    constraint graphs and full engine traffic (fleet sizes 1/3/8) —
    not "close", byte-equal.
  * DEGENERATE — no constraints = identity, single-node graphs,
    saturating-score false candidates rejected by the contrast gate.
  * DRIFT — on a return-to-start trace with injected per-revolution
    drift the corrected end pose lands within 2 map cells while the
    front-end-only baseline error is the full injected drift (the
    ISSUE-11 acceptance bar; config 17 asserts the same at bench
    geometry).
  * CHECKPOINT — snapshot/restore (full, per-stream, cross-backend)
    resumes bit-exactly; versioned schema rejects mismatches.
  * WIRING — service attach seam, /diagnostics rendering, replay
    --loop-close, node lifecycle + combined checkpoint.
"""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.mapping.mapper import FleetMapper
from rplidar_ros2_driver_tpu.ops.pose_graph import (
    PoseGraphConfig,
    fleet_solve_pose_graph,
    solve_pose_graph,
)
from rplidar_ros2_driver_tpu.ops.pose_graph_ref import (
    pose_compose_np,
    pose_relative_np,
    rel_inverse_np,
    solve_pose_graph_np,
)
from rplidar_ros2_driver_tpu.ops.scan_match import SUB, rotation_table
from rplidar_ros2_driver_tpu.slam.loop import LoopClosureEngine

BEAMS = 128


def _params(**kw) -> DriverParams:
    base = dict(
        dummy_mode=True,
        filter_backend="cpu",
        filter_chain=("clip", "median", "voxel"),
        map_enable=True,
        map_backend="host",
        map_grid=64,
        map_cell_m=0.1,
        loop_enable=True,
        loop_backend="host",
        loop_submap_revs=3,
        loop_check_revs=2,
        loop_max_submaps=6,
        loop_candidates=2,
        pose_graph_iters=64,
    )
    base.update(kw)
    return DriverParams(**base)


def _room_points(pose_xyt, n: int = BEAMS, half: float = 2.5):
    t = np.linspace(0, 2 * np.pi, n, endpoint=False)
    dx, dy = np.cos(t), np.sin(t)
    with np.errstate(divide="ignore"):
        r = np.minimum(
            np.where(np.abs(dx) > 1e-12, half / np.abs(dx), np.inf),
            np.where(np.abs(dy) > 1e-12, half / np.abs(dy), np.inf),
        )
    wx, wy = dx * r, dy * r
    x0, y0, th = pose_xyt
    c, s = np.cos(-th), np.sin(-th)
    px = c * (wx - x0) - s * (wy - y0)
    py = s * (wx - x0) + c * (wy - y0)
    return np.stack([px, py], 1).astype(np.float32), np.ones(n, bool)


# ---------------------------------------------------------------------------
# config / params
# ---------------------------------------------------------------------------


class TestConfig:
    def test_param_validation(self):
        def validate(**kw):
            _params(**kw).validate()

        validate()
        with pytest.raises(ValueError, match="loop_backend"):
            validate(loop_backend="gpu")
        with pytest.raises(ValueError, match="map_enable"):
            DriverParams(loop_enable=True).validate()
        with pytest.raises(ValueError, match="loop_max_submaps"):
            validate(loop_max_submaps=1)
        with pytest.raises(ValueError, match="loop_candidates"):
            validate(loop_candidates=99)
        with pytest.raises(ValueError, match="loop_submap_revs"):
            validate(loop_submap_revs=0)
        with pytest.raises(ValueError, match="loop_check_revs"):
            validate(loop_check_revs=0)
        with pytest.raises(ValueError, match="loop_accept_shift"):
            validate(loop_accept_shift=99)
        with pytest.raises(ValueError, match="loop_weight"):
            validate(loop_weight=0)
        with pytest.raises(ValueError, match="pose_graph_iters"):
            validate(pose_graph_iters=0)
        with pytest.raises(ValueError, match="pose_graph_max_constraints"):
            validate(pose_graph_max_constraints=0)

    def test_pose_graph_config_overflow_guard(self):
        with pytest.raises(ValueError, match="int32"):
            PoseGraphConfig(
                max_nodes=64, max_constraints=100000,
                t_limit_sub=16384, weight_max=16,
            )

    def test_loop_config_derivation(self):
        from rplidar_ros2_driver_tpu.slam.loop import loop_config_from_params
        from rplidar_ros2_driver_tpu.mapping.mapper import (
            map_config_from_params,
        )

        p = _params()
        mc = map_config_from_params(p, BEAMS)
        lc = loop_config_from_params(p, mc)
        # stored planes are pre-quantized: the derived config's in-
        # kernel clip >> shift must be the identity on them
        assert lc.match.quant_shift == 0
        assert lc.match.clamp_q == mc.clamp_q >> mc.quant_shift
        assert lc.graph.max_nodes == p.loop_max_submaps
        assert lc.graph.theta_divisions == mc.theta_divisions
        # accept gate product stays in int32 (validated in LoopConfig)
        assert lc.accept_q * lc.match.beams < 2**31


# ---------------------------------------------------------------------------
# solver: golden convergence + parity + degenerates
# ---------------------------------------------------------------------------


def _chain_cfg(k=8, c=24, iters=96):
    return PoseGraphConfig(
        max_nodes=k, max_constraints=c, iters=iters, t_limit_sub=4096
    )


class TestPoseGraphSolver:
    def test_golden_loop_relaxes_to_lattice(self):
        """A 5-node chain with 1 cell/step injected drift and a strong
        loop constraint back to the anchor must relax the end node to
        within one map cell (SUB subcells) of truth."""
        cfg = _chain_cfg()
        nodes = np.zeros((8, 3), np.int32)
        drift = SUB  # injected drift per odometry step (1 cell)
        true_step = 10 * SUB
        for k in range(1, 5):
            nodes[k] = [(true_step + drift) * k, 0, 0]
        cons = np.zeros((24, 6), np.int32)
        for k in range(1, 5):
            cons[k - 1] = [k - 1, k, true_step + drift, 0, 0, 1]
        cons[4] = [0, 4, 4 * true_step, 0, 0, 8]  # the truth, strongly held
        got = solve_pose_graph_np(nodes, cons, cfg)
        assert abs(int(got[4, 0]) - 4 * true_step) <= SUB
        # interior nodes share the correction monotonically
        xs = got[:5, 0]
        assert all(xs[i] < xs[i + 1] for i in range(4))

    def test_golden_rotation_loop(self):
        """Heading drift relaxes too: a loop whose θ legs disagree by
        8 table steps lands the end node within 2 steps of truth."""
        cfg = _chain_cfg()
        nodes = np.zeros((8, 3), np.int32)
        for k in range(1, 5):
            nodes[k] = [600 * k, 0, (10 + 2) * k]  # 2 steps/leg drift
        cons = np.zeros((24, 6), np.int32)
        for k in range(1, 5):
            cons[k - 1] = [k - 1, k, 600, 0, 12, 1]
        cons[4] = [0, 4, 2400, 0, 40, 8]  # true total heading 40 steps
        got = solve_pose_graph_np(nodes, cons, cfg)
        assert abs(int(got[4, 2]) - 40) <= 2

    def test_no_constraints_is_identity(self):
        cfg = _chain_cfg()
        rng = np.random.default_rng(1)
        nodes = rng.integers(-2000, 2000, (8, 3)).astype(np.int32)
        nodes[:, 2] = rng.integers(0, 720, 8)
        cons = np.zeros((24, 6), np.int32)  # all padding (weight 0)
        np.testing.assert_array_equal(
            solve_pose_graph_np(nodes, cons, cfg), nodes
        )
        np.testing.assert_array_equal(
            np.asarray(solve_pose_graph(nodes, cons, cfg)), nodes
        )

    def test_single_node_graph(self):
        cfg = PoseGraphConfig(max_nodes=1, max_constraints=4, iters=8)
        nodes = np.asarray([[100, -50, 3]], np.int32)
        cons = np.zeros((4, 6), np.int32)
        cons[0] = [0, 0, 5, 5, 1, 4]  # self-loop on the gauge anchor
        got = solve_pose_graph_np(nodes, cons, cfg)
        np.testing.assert_array_equal(got, nodes)  # anchor never moves
        np.testing.assert_array_equal(
            np.asarray(solve_pose_graph(nodes, cons, cfg)), got
        )

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_randomized_graph_parity(self, seed):
        """jnp vs numpy byte parity over randomized dense graphs —
        including out-of-range indices (clipped), zero weights
        (padding) and saturating z terms (clamped)."""
        cfg = _chain_cfg()
        rng = np.random.default_rng(seed)
        nodes = rng.integers(-4000, 4000, (8, 3)).astype(np.int32)
        nodes[:, 2] = rng.integers(0, 720, 8)
        nodes[0] = 0
        cons = np.zeros((24, 6), np.int32)
        n = int(rng.integers(1, 24))
        cons[:n, 0] = rng.integers(-2, 10, n)       # some out of range
        cons[:n, 1] = rng.integers(-2, 10, n)
        cons[:n, 2:4] = rng.integers(-20000, 20000, (n, 2))  # some clamp
        cons[:n, 4] = rng.integers(-1000, 1000, n)
        cons[:n, 5] = rng.integers(0, 30, n)        # some pad, some clamp
        ref = solve_pose_graph_np(nodes, cons, cfg)
        np.testing.assert_array_equal(
            np.asarray(solve_pose_graph(nodes, cons, cfg)), ref
        )

    def test_fleet_vmap_parity(self):
        cfg = _chain_cfg()
        rng = np.random.default_rng(7)
        nodes = rng.integers(-3000, 3000, (3, 8, 3)).astype(np.int32)
        nodes[:, :, 2] = rng.integers(0, 720, (3, 8))
        cons = np.zeros((3, 24, 6), np.int32)
        cons[:, :5, 0] = rng.integers(0, 8, (3, 5))
        cons[:, :5, 1] = rng.integers(0, 8, (3, 5))
        cons[:, :5, 2:5] = rng.integers(-3000, 3000, (3, 5, 3))
        cons[:, :5, 5] = rng.integers(1, 16, (3, 5))
        got = np.asarray(fleet_solve_pose_graph(nodes, cons, cfg))
        for s in range(3):
            np.testing.assert_array_equal(
                got[s], solve_pose_graph_np(nodes[s], cons[s], cfg)
            )

    def test_pose_helper_roundtrips(self):
        """compose(a, relative(a, b)) ≈ b and z ∘ z⁻¹ ≈ identity to the
        rotation core's rounding (±1 subcell)."""
        table = rotation_table(720)
        rng = np.random.default_rng(3)
        for _ in range(20):
            a = rng.integers(-2000, 2000, 3).astype(np.int32)
            b = rng.integers(-2000, 2000, 3).astype(np.int32)
            a[2], b[2] = rng.integers(0, 720, 2)
            z = pose_relative_np(a, b, table, 720)
            back = pose_compose_np(a, z, table, 720)
            assert np.abs(back[:2] - b[:2]).max() <= 1
            assert back[2] == b[2]
            zi = rel_inverse_np(z, table, 720)
            ident = pose_compose_np(
                pose_compose_np(a, z, table, 720), zi, table, 720
            )
            assert np.abs(ident[:2] - a[:2]).max() <= 2
            assert ident[2] == a[2]


# ---------------------------------------------------------------------------
# engine: fleet parity + degenerates + checkpoint
# ---------------------------------------------------------------------------


def _drive(backend, streams, ticks=14, **param_kw):
    p = _params(loop_backend=backend, **param_kw)
    mapper = FleetMapper(p, streams, beams=BEAMS)
    eng = LoopClosureEngine(p, mapper)
    if eng.backend == "fused":
        eng.precompile()
    log = []
    for k in range(ticks):
        pts = np.zeros((streams, BEAMS, 2), np.float32)
        masks = np.zeros((streams, BEAMS), bool)
        live = np.zeros((streams,), np.int32)
        for s in range(streams):
            if (k + s) % 5 == 4:
                continue  # idle this tick
            pp, mm = _room_points(
                (0.05 * k * (1 + 0.2 * s), -0.03 * k, 0.002 * k)
            )
            rng = np.random.default_rng(10 * s + k)
            mm &= rng.uniform(size=BEAMS) > 0.05
            pts[s], masks[s] = pp, mm
            live[s] = 1
        ests = mapper.submit_points(pts, masks, live)
        sts = eng.observe(ests)
        log.append([
            None if st is None else (
                st.accepted, st.candidate, st.score, st.matched_points,
                tuple(int(v) for v in st.corrected_q),
                st.constraints, st.dropped,
            )
            for st in sts
        ])
    return eng, log


class TestEngineParity:
    @pytest.mark.parametrize("streams", [1, 3, 8])
    def test_fused_bit_exact_vs_host(self, streams):
        eh, lh = _drive("host", streams)
        ef, lf = _drive("fused", streams)
        assert eh.backend == "host" and ef.backend == "fused"
        assert lh == lf
        sh, sf = eh.snapshot(), ef.snapshot()
        assert set(sh) == set(sf)
        for k in sh:
            np.testing.assert_array_equal(sh[k], sf[k])
        # structural: one batched dispatch per closure-check tick
        assert ef.dispatch_count > 0
        assert ef.checks >= ef.dispatch_count

    def test_reanchor_mode_parity_and_effect(self):
        """loop_reanchor rewrites the front-end pose on accept — both
        backends identically, and the engine's standing correction
        resets (the front-end then carries it)."""
        eh, lh = _drive("host", 2, loop_reanchor=True)
        ef, lf = _drive("fused", 2, loop_reanchor=True)
        assert lh == lf
        for k, v in eh.snapshot().items():
            np.testing.assert_array_equal(v, ef.snapshot()[k])
        assert eh.closures_accepted.sum() > 0
        np.testing.assert_array_equal(eh._corr, 0)

    def test_pallas_match_backend_rides_candidate_scoring(self):
        """match_backend=pallas routes the candidate score volumes
        through the PR 8 kernels (interpret mode on CPU) — byte-equal
        to the XLA arm and the host reference."""
        a = _drive("host", 1, ticks=8)[1]
        b = _drive("fused", 1, ticks=8, match_backend="pallas")[1]
        c = _drive("fused", 1, ticks=8, match_backend="xla")[1]
        assert a == b == c


class TestDegenerate:
    def test_saturating_false_candidate_rejected(self):
        """A submap plane saturated to the clamp everywhere scores
        maximal-and-FLAT across the whole (dθ, dx, dy) volume: the
        peak-contrast gate must reject it (an absolute bar alone would
        accept this false positive)."""
        from rplidar_ros2_driver_tpu.ops.loop_close_ref import (
            create_loop_state_np,
            install_submap_np,
            loop_close_step_np,
        )
        from rplidar_ros2_driver_tpu.slam.loop import loop_config_from_params
        from rplidar_ros2_driver_tpu.mapping.mapper import (
            map_config_from_params,
        )

        p = _params()
        cfg = loop_config_from_params(p, map_config_from_params(p, BEAMS))
        st = create_loop_state_np(cfg)
        g = cfg.match.grid
        sat = np.full((g, g), cfg.match.clamp_q, np.int32)
        st = install_submap_np(st, sat, np.zeros(3, np.int32), cfg)
        st = install_submap_np(st, sat, np.asarray([64, 0, 0], np.int32), cfg)
        # a room small enough that every (dθ, dx, dy) candidate keeps
        # every endpoint inside the grid: the saturated plane then
        # scores EXACTLY flat (edge fall-off would otherwise fake the
        # contrast a real structured match earns)
        pts, m = _room_points((0, 0, 0), half=1.2)
        new, wire, _ = loop_close_step_np(
            st, pts, m, np.zeros(3, np.int32),
            np.asarray([0, -1], np.int32), 1, cfg,
        )
        assert wire[0] == 0          # rejected
        assert wire[2] > 0           # ...despite a huge raw score
        assert int(new["ncons"]) == 0

    def test_check_without_candidates_is_noop(self):
        """check=1 with an empty candidate list (all -1) must pass the
        state through and wire the no-candidate sentinel."""
        from rplidar_ros2_driver_tpu.ops.loop_close import (
            LoopState,
            loop_close_step,
        )
        from rplidar_ros2_driver_tpu.ops.loop_close_ref import (
            create_loop_state_np,
            loop_close_step_np,
        )
        from rplidar_ros2_driver_tpu.slam.loop import loop_config_from_params
        from rplidar_ros2_driver_tpu.mapping.mapper import (
            map_config_from_params,
        )

        p = _params()
        cfg = loop_config_from_params(p, map_config_from_params(p, BEAMS))
        st_np = create_loop_state_np(cfg)
        pts, m = _room_points((0, 0, 0))
        pose = np.asarray([10, 20, 3], np.int32)
        cand = np.full((cfg.candidates,), -1, np.int32)
        new_np, wire_np, _ = loop_close_step_np(
            st_np, pts, m, pose, cand, 1, cfg
        )
        assert wire_np[0] == 0 and wire_np[1] == -1 and wire_np[2] == 0
        np.testing.assert_array_equal(wire_np[4:7], pose)  # empty = identity
        st_j = LoopState.create(cfg)
        _, wire_j, _ = loop_close_step(
            st_j, pts, m, pose, cand, np.int32(1), cfg=cfg
        )
        np.testing.assert_array_equal(np.asarray(wire_j), wire_np)

    def test_library_caps_and_holds(self):
        """The library freezes at loop_max_submaps — node indices must
        stay stable for the constraints that reference them."""
        eng, _ = _drive("host", 1, ticks=30, loop_submap_revs=1,
                        loop_max_submaps=4)
        assert int(eng._count[0]) == 4
        snap = eng.snapshot()
        assert int(snap["count"][0]) == 4
        assert snap["valid"][0].sum() == 4


class TestCheckpoint:
    def test_snapshot_restore_roundtrip_cross_backend(self):
        eh, _ = _drive("host", 2)
        snap = eh.snapshot()
        ef, _ = _drive("fused", 2)
        assert ef.restore(snap) is True
        back = ef.snapshot()
        for k in snap:
            np.testing.assert_array_equal(snap[k], back[k])

    def test_stream_row_roundtrip_and_rejects(self):
        eh, _ = _drive("host", 2)
        ef, _ = _drive("fused", 2)
        row = eh.snapshot_stream(1)
        assert ef.restore_stream(0, row) is True
        got = ef.snapshot_stream(0)
        for k in row:
            np.testing.assert_array_equal(row[k], got[k])
        bad = dict(row)
        bad["version"] = np.asarray(99, np.int32)
        assert ef.restore_stream(0, bad) is False
        small, _ = _drive("host", 1, loop_max_submaps=4)
        assert small.restore_stream(0, row) is False  # geometry mismatch
        assert small.restore(eh.snapshot()) is False

    def test_restore_resumes_bit_exact(self):
        """Mid-run snapshot -> fresh engine restore -> identical tail
        (the parity bar across the snapshot/restore path)."""
        p = _params()
        mapper = FleetMapper(p, 1, beams=BEAMS)
        eng = LoopClosureEngine(p, mapper)
        tick_data = []
        for k in range(12):
            pts, m = _room_points((0.05 * k, -0.02 * k, 0.002 * k))
            tick_data.append((pts, m))
        for pts, m in tick_data[:6]:
            ests = mapper.submit_points(
                pts[None], m[None], np.ones(1, np.int32)
            )
            eng.observe(ests)
        map_snap, loop_snap = mapper.snapshot(), eng.snapshot()
        ref = []
        for pts, m in tick_data[6:]:
            ests = mapper.submit_points(
                pts[None], m[None], np.ones(1, np.int32)
            )
            st = eng.observe(ests)[0]
            ref.append(None if st is None else tuple(st.corrected_q))
        m2 = FleetMapper(p, 1, beams=BEAMS)
        assert m2.restore(map_snap)
        e2 = LoopClosureEngine(p, m2)
        assert e2.restore(loop_snap)
        # resync the revolution bookkeeping the snapshot doesn't carry
        e2._last_final_rev[:] = eng._last_final_rev
        e2._last_check_rev[:] = 0
        got = []
        for pts, m in tick_data[6:]:
            ests = m2.submit_points(
                pts[None], m[None], np.ones(1, np.int32)
            )
            st = e2.observe(ests)[0]
            got.append(None if st is None else tuple(st.corrected_q))
        assert ref == got


# ---------------------------------------------------------------------------
# drift golden: the ISSUE-11 acceptance scenario at test geometry
# ---------------------------------------------------------------------------


class TestDriftCorrection:
    @pytest.mark.parametrize("backend", ["host", "fused"])
    def test_return_to_start_drift_bounded(self, backend):
        """Injected per-revolution drift on a return-to-start trace:
        the front-end-only baseline error is the full injected drift
        (unbounded in trace length) while the pose-graph-corrected end
        pose lands within 2 map cells (config 17 asserts the same at
        bench geometry with the steady-state guard around it)."""
        import bench

        streams, n_revs, drift_sub = 1, 24, SUB // 2
        p = _params(
            loop_backend=backend, loop_submap_revs=4, loop_check_revs=2,
            loop_max_submaps=8, loop_weight=8,
            pose_graph_max_constraints=32, pose_graph_iters=96,
        )
        fe = bench._DriftingFrontEnd(p, streams, BEAMS, p.loop_submap_revs)
        eng = LoopClosureEngine(p, fe)
        eng.precompile()
        revs, masks, true_end = bench._loop_drift_trace(
            streams, BEAMS, n_revs, drift_sub, p.map_cell_m
        )
        for pts, drifted in revs:
            eng.observe(fe.submit(pts, masks, drifted))
        end = fe.pose[0]
        baseline_cells = abs(int(end[0]) - int(true_end[0][0])) / SUB
        cor = eng.corrected_pose_q(0, end)
        corrected_cells = (
            abs(int(cor[0]) - int(true_end[0][0]))
            + abs(int(cor[1]) - int(true_end[0][1]))
        ) / SUB
        assert baseline_cells >= 4.0          # drifts without bound
        assert corrected_cells <= 2.0         # the acceptance bar
        assert eng.closures_accepted.sum() > 0


# ---------------------------------------------------------------------------
# wiring: service seam, diagnostics, replay, node
# ---------------------------------------------------------------------------


def _scan(k: int, points: int = 300) -> dict:
    rng = np.random.default_rng(k)
    return {
        "angle_q14": ((np.arange(points) * 65536) // points).astype(np.int32),
        "dist_q2": (rng.uniform(0.3, 8.0, points) * 4000).astype(np.int32),
        "quality": np.full(points, 180, np.int32),
        "flag": None,
    }


def test_service_attach_loop_closure():
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
    from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh

    svc = ShardedFilterService(
        _params(filter_window=2, voxel_grid_size=32, loop_submap_revs=2,
                loop_check_revs=1),
        streams=2, mesh=make_mesh(2), beams=128,
    )
    eng = svc.attach_loop_closure()
    assert svc.mapper is not None and eng.streams == 2
    for k in range(5):
        svc.submit([_scan(2 * k), _scan(2 * k + 1)])
    assert eng.ticks == 5
    assert all(c > 0 for c in eng._count)      # submaps finalized
    assert svc.loop_status() is not None
    assert any(p is not None for p in svc.last_corrected_poses)
    # failover transport: the per-stream bundle now carries the loop row
    svc._quarantine_stream(0)
    snap = svc.stream_checkpoints[0]
    assert "loop" in snap and "map" in snap
    svc._rejoin_stream(0)


def test_diagnostics_loop_group_rendering():
    from rplidar_ros2_driver_tpu.node.diagnostics import DiagnosticsUpdater
    from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState

    class _Pub:
        def publish_diagnostics(self, status):
            self.last = status

    upd = DiagnosticsUpdater("rplidar-test", _Pub())
    status = upd.update(
        lifecycle=LifecycleState.ACTIVE,
        fsm_state=None,
        port="/dev/x", rpm=600, device_info="sim",
        loop_status={
            "backend": "host",
            "submaps": [4, 3],
            "accepted": 5,
            "rejected": 2,
            "constraints": 5,
            "last_closure_tick": 17,
            "correction_m": (0.125, -0.03, 0.0044),
        },
    )
    v = status.values
    assert v["Loop Closures"] == "5 accepted / 2 rejected"
    assert v["Loop Submaps"] == "4,3"
    assert v["Loop Constraints"] == "5"
    assert v["Last Closure Tick"] == "17"
    assert v["Pose Correction"] == "+0.125 -0.030 +0.0044"
    # absent group renders nothing
    status = upd.update(
        lifecycle=LifecycleState.ACTIVE, fsm_state=None,
        port="/dev/x", rpm=600, device_info="sim",
    )
    assert "Loop Closures" not in status.values


def test_replay_with_loop_closure():
    from rplidar_ros2_driver_tpu.replay import replay_with_loop_closure

    revs = [_scan(k, points=600) for k in range(8)]
    traj, corrected, scores, mapper, engine = replay_with_loop_closure(
        revs,
        _params(filter_window=2, voxel_grid_size=32, loop_submap_revs=2,
                loop_check_revs=2),
        beams=256,
    )
    assert traj.shape == corrected.shape == (8, 3)
    assert np.isfinite(traj).all() and np.isfinite(corrected).all()
    assert scores.shape == (8,)
    assert engine.ticks == 8
    assert int(engine._count[0]) > 0


class TestNodeWiring:
    def _node_params(self):
        return _params(
            voxel_grid_size=32, filter_window=2,
            loop_submap_revs=2, loop_check_revs=2,
        )

    def _fake_output(self, beams=2048):
        from rplidar_ros2_driver_tpu.ops.filters import FilterOutput

        pts, m = _room_points((0, 0, 0), n=beams)
        return FilterOutput(
            ranges=np.linalg.norm(pts, axis=1).astype(np.float32),
            intensities=np.full(beams, 47.0, np.float32),
            points_xy=pts,
            point_mask=m,
            voxel=np.zeros((32, 32), np.int32),
        )

    def test_node_lifecycle_and_diagnostics(self):
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode

        node = RPlidarNode(self._node_params())
        assert node.configure()
        assert node.loop is not None
        for _ in range(4):
            node._publish_chain_output(self._fake_output(), 1.0, 0.1, 8.0)
        assert node.publisher.poses  # corrected pose republished
        node._update_diagnostics()
        values = node.publisher.diagnostics[-1].values
        assert "Loop Closures" in values
        assert "Loop Submaps" in values

    def test_node_checkpoint_roundtrips_loop_state(self, tmp_path):
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode

        node = RPlidarNode(self._node_params())
        assert node.configure()
        for _ in range(4):
            node._publish_chain_output(self._fake_output(), 1.0, 0.1, 8.0)
        want = node.loop.snapshot()
        assert int(want["count"][0]) > 0
        path = str(tmp_path / "node_loop_ckpt.npz")
        assert node.save_checkpoint(path) is True

        fresh = RPlidarNode(self._node_params())
        assert fresh.load_checkpoint(path) is True
        assert fresh.configure()
        got = fresh.loop.snapshot()
        for k in want:
            np.testing.assert_array_equal(want[k], got[k])
