"""Traffic-shaped elastic serving: the policy layer
(parallel/scheduler.py) and its wiring through the engines, the
topology and the pod (ROADMAP item 4).

The acceptance contract this suite pins:

  * **Backlog-adaptive rung depth** — the RungLadder steps UP
    immediately on a burst, DOWN only after the hysteresis streak, and
    the deadline budget CAPS the pick from the measured per-tick drain
    cost; the FleetFusedIngest rung ladder warms every depth at
    precompile, refuses unwarmed depths and late extensions, and a
    backlog drained at ANY rung sequence is byte-exact against the
    per-tick host reference (the policy chooses when, never what).
  * **SLO-aware admission** — per-stream queues are BOUNDED: past
    ``admission_max_backlog_ticks`` the oldest tick is shed with
    per-stream counters, never unbounded growth.
  * **Byte-rate-weighted placement** — FleetTopology loads are
    weighted sums; assign/evacuate/rebalance land hot streams on cold
    shards, heaviest first, and degrade exactly to the stream-count
    heuristic at the default weight 1.0.
  * The serving seams (ShardedFilterService.offer_bytes/
    drain_scheduled, the ElasticFleetService pod analog) and the
    /diagnostics scheduler value-group rendering.
"""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest
from rplidar_ros2_driver_tpu.parallel.scheduler import (
    BucketLadder,
    ByteRateEwma,
    LatencyModel,
    RungLadder,
    SchedulerConfig,
    TrafficShaper,
)
from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
from rplidar_ros2_driver_tpu.parallel.sharding import FleetTopology
from rplidar_ros2_driver_tpu.protocol.constants import Ans

from test_fused_ingest import BEAMS, _params
from test_fleet_fused_ingest import (
    _assert_fleet_outputs_equal,
    _host_reference,
    _mk_ticks,
)
from test_live_decode import _make_stream

DENSE = int(Ans.MEASUREMENT_DENSE_CAPSULED)


# ---------------------------------------------------------------------------
# config + policy units (no device work)
# ---------------------------------------------------------------------------


class TestSchedulerConfig:
    def test_from_params_reads_the_sched_surface(self):
        p = _params(
            sched_rungs=(1, 3, 9), sched_hysteresis_ticks=5,
            sched_deadline_ms=7.5, sched_byte_rate_alpha=0.5,
            admission_max_backlog_ticks=11,
            bucket_rungs=(4, 16), occupancy_alpha=0.4,
        )
        cfg = SchedulerConfig.from_params(p)
        assert cfg.rungs == (1, 3, 9)
        assert cfg.hysteresis_ticks == 5
        assert cfg.deadline_ms == 7.5
        assert cfg.byte_rate_alpha == 0.5
        assert cfg.max_backlog_ticks == 11
        assert cfg.bucket_rungs == (4, 16)
        assert cfg.occupancy_alpha == 0.4

    @pytest.mark.parametrize("bad", [
        dict(rungs=()),
        dict(rungs=(2, 4)),          # must start at 1
        dict(rungs=(1, 4, 2)),       # must ascend
        dict(hysteresis_ticks=0),
        dict(deadline_ms=-1.0),
        dict(byte_rate_alpha=0.0),
        dict(byte_rate_alpha=1.5),
        dict(max_backlog_ticks=0),   # the backlog is bounded by contract
        dict(rungs=(1, 128)),        # compile-cost cap (one program/bucket)
        dict(bucket_rungs=(0, 4)),   # buckets must be >= 1
        dict(bucket_rungs=(8, 4)),   # buckets must ascend
        dict(occupancy_alpha=0.0),
        dict(occupancy_alpha=1.5),
        dict(steal_threshold_ticks=-1),
        dict(steal_headroom_ms=-0.5),
        # the reserve must leave part of the deadline as drain budget
        dict(deadline_ms=5.0, steal_headroom_ms=5.0),
        dict(autoscale_low_watermark=0.0),
        dict(autoscale_low_watermark=0.8,
             autoscale_high_watermark=0.5),   # low < high
        dict(autoscale_high_watermark=1.5),
        dict(autoscale_hysteresis_ticks=0),
        dict(autoscale_min_shards=0),
        dict(autoscale_rate_floor=0.0),       # liveness needs a floor
    ])
    def test_rejects_invalid(self, bad):
        with pytest.raises(ValueError):
            SchedulerConfig(**bad)

    def test_from_params_reads_the_steal_autoscale_surface(self):
        p = _params(
            steal_threshold_ticks=3, steal_headroom_ms=1.5,
            autoscale_enable=True, autoscale_low_watermark=0.2,
            autoscale_high_watermark=0.8, autoscale_hysteresis_ticks=5,
            autoscale_min_shards=2, autoscale_rate_floor=128.0,
        )
        cfg = SchedulerConfig.from_params(p)
        assert cfg.steal_threshold_ticks == 3
        assert cfg.steal_headroom_ms == 1.5
        assert cfg.autoscale_enable is True
        assert cfg.autoscale_low_watermark == 0.2
        assert cfg.autoscale_high_watermark == 0.8
        assert cfg.autoscale_hysteresis_ticks == 5
        assert cfg.autoscale_min_shards == 2
        assert cfg.autoscale_rate_floor == 128.0


class TestByteRateEwma:
    def test_tracks_and_decays(self):
        r = ByteRateEwma(2, alpha=0.5)
        assert r.rates() == [0.0, 0.0]
        r.note(0, 1000)
        assert r.rates()[0] == 1000.0  # first observation seeds
        r.note(0, 0)   # idle tick: the estimate decays
        assert r.rates()[0] == 500.0
        r.note(1, 100)
        r.note(1, 300)
        assert r.rates()[1] == 200.0


class TestRungLadder:
    def test_up_immediate_down_hysteresis(self):
        lad = RungLadder(SchedulerConfig(
            rungs=(1, 2, 4, 8), hysteresis_ticks=2,
        ))
        assert lad.pick(1) == 1
        assert lad.pick(7) == 8          # burst: straight to the top
        assert lad.pick(1) == 8          # low streak 1 of 2: hold
        assert lad.pick(1) == 4          # streak complete: ONE step down
        assert lad.pick(1) == 4          # streak reset by the step
        assert lad.pick(1) == 2
        assert lad.pick(3) == 4          # demand re-raises immediately

    def test_sawtooth_does_not_thrash(self):
        lad = RungLadder(SchedulerConfig(
            rungs=(1, 2, 4), hysteresis_ticks=3,
        ))
        lad.pick(4)
        # alternating 1/4 backlog: the low streak never completes
        picks = [lad.pick(1), lad.pick(4), lad.pick(1), lad.pick(4)]
        assert picks == [4, 4, 4, 4]

    def test_deadline_budget_caps_the_pick(self):
        lad = RungLadder(SchedulerConfig(
            rungs=(1, 2, 4, 8), hysteresis_ticks=1, deadline_ms=10.0,
        ))
        # measured 3 ms/tick: 8 * 3 = 24 ms and 4 * 3 = 12 ms blow the
        # 10 ms budget, 2 * 3 = 6 ms fits
        lad.note_drain(4, 0.012)
        assert lad.pick(8) == 2
        # the demand level survived the cap: with a looser measured
        # cost the same ladder serves the full rung again
        lad.tick_cost_ema = 1e-4
        assert lad.pick(8) == 8

    def test_deadline_never_caps_below_the_floor_rung(self):
        lad = RungLadder(SchedulerConfig(
            rungs=(1, 4), hysteresis_ticks=1, deadline_ms=0.001,
        ))
        lad.note_drain(1, 10.0)  # 10 s/tick: nothing fits the budget
        assert lad.pick(4) == 1

    def test_model_cost_outranks_the_scalar_extrapolation(self):
        """The measured (rung, bucket) entry prices the deadline cap;
        the scalar EWMA — which extrapolates linearly and so mis-prices
        the super-step's amortization — only prices rungs the table
        has never seen."""
        model = LatencyModel()
        lad = RungLadder(SchedulerConfig(
            rungs=(1, 2, 4, 8), hysteresis_ticks=1, deadline_ms=10.0,
        ), model=model)
        # scalar says 3 ms/tick -> rung 8 extrapolates to 24 ms (over
        # budget), but the MEASURED rung-8 dispatch amortizes to 8 ms
        lad.note_drain(4, 0.012)
        model.note(8, 4, 0.008)
        assert lad.pick(8, bucket=4) == 8
        # a different bucket has no entry: the scalar fallback caps
        assert lad.pick(8, bucket=16) == 2

    def test_note_drain_refits_the_model_per_dispatch(self):
        model = LatencyModel()
        lad = RungLadder(SchedulerConfig(rungs=(1, 2, 4)), model=model)
        # 7 ticks at rung 4 = ceil(7/4) = 2 dispatches of 6 ms each
        lad.note_drain(7, 0.012, rung=4, bucket=8)
        assert model.cost(4, 8) == pytest.approx(0.006)
        # no bucket identity: the table is untouched, the scalar still
        # updates (the model-less fallback predictor)
        lad.note_drain(2, 0.004, rung=2)
        assert model.cost(2, None) is None
        assert lad.tick_cost_ema is not None


class TestLatencyModel:
    def test_seed_prices_before_traffic_and_live_replaces(self):
        m = LatencyModel()
        m.seed(4, 8, 0.010)
        assert m.cost(4, 8) == pytest.approx(0.010)
        m.seed(4, 8, 0.999)        # re-seeding an existing key: no-op
        assert m.cost(4, 8) == pytest.approx(0.010)
        m.note(4, 8, 0.002)        # first live measurement REPLACES
        assert m.cost(4, 8) == pytest.approx(0.002)
        m.note(4, 8, 0.004)        # then the EWMA folds (ALPHA=0.2)
        assert m.cost(4, 8) == pytest.approx(0.8 * 0.002 + 0.2 * 0.004)

    def test_seed_many_and_invalid_seeds_ignored(self):
        m = LatencyModel()
        m.seed_many({(1, 4): 0.001, (2, 4): 0.0015})
        assert m.cost(1, 4) == pytest.approx(0.001)
        m.seed(1, 8, 0.0)          # non-positive: ignored
        assert m.cost(1, 8) is None

    def test_no_bucket_returns_the_worst_cost_at_the_rung(self):
        """With no bucket identity the deadline must use a SAFE bound:
        the most expensive fitted executable at that rung."""
        m = LatencyModel()
        m.note(4, 4, 0.002)
        m.note(4, 16, 0.005)
        assert m.cost(4, None) == pytest.approx(0.005)
        assert m.cost(2, None) is None

    def test_table_ms_rendering_keys(self):
        m = LatencyModel()
        m.note(2, 16, 0.0015)
        m.note(1, 4, 0.0005)
        assert m.table_ms() == {"T1xM4": 0.5, "T2xM16": 1.5}


class TestBucketLadder:
    def test_starts_at_the_top_bucket(self):
        lad = BucketLadder((4, 8, 16), hysteresis_ticks=2, alpha=1.0)
        assert lad.bucket == 16
        assert lad.pick() == 16    # no occupancy observed yet: hold

    def test_collapse_immediate_recovery_hysteretic(self):
        # alpha=1.0: the EWMA is the raw observation, so the threshold
        # arithmetic is exact
        lad = BucketLadder((4, 8), hysteresis_ticks=2, alpha=1.0)
        lad.note_occupancy(0, 4)       # fleet collapsed
        assert lad.pick() == 4         # DOWN is immediate
        assert lad.switches == 1
        lad.note_occupancy(4, 4)       # recovered
        assert lad.pick() == 4         # high streak 1 of 2: hold
        lad.note_occupancy(4, 4)
        assert lad.pick() == 8         # streak complete: ONE step up
        assert lad.switches == 2

    def test_recovery_streak_resets_on_a_dip(self):
        lad = BucketLadder((4, 8), hysteresis_ticks=2, alpha=1.0)
        lad.note_occupancy(0, 4)
        lad.pick()
        lad.note_occupancy(4, 4)
        lad.pick()                     # streak 1
        lad.note_occupancy(0, 4)
        assert lad.pick() == 4         # dip: target == idx, streak reset
        lad.note_occupancy(4, 4)
        assert lad.pick() == 4         # streak must rebuild from 1
        lad.note_occupancy(4, 4)
        assert lad.pick() == 8

    def test_evenly_spaced_thresholds(self):
        """Bucket index i needs the EWMA strictly above i/n — a
        half-quarantined fleet sits at the floor of a 2-bucket
        ladder."""
        lad = BucketLadder((4, 8), hysteresis_ticks=1, alpha=1.0)
        lad.note_occupancy(2, 4)       # exactly 0.5: NOT above 1/2
        assert lad.pick() == 4
        lad.note_occupancy(3, 4)       # 0.75 > 0.5
        assert lad.pick() == 8

    def test_occupancy_ewma_smooths_a_flap(self):
        # alpha=0.2 from 1.0: one idle drain only drags the EWMA to
        # 0.8 — a single flapping tick cannot collapse the cap
        lad = BucketLadder((4, 8), hysteresis_ticks=2, alpha=0.2)
        lad.note_occupancy(4, 4)
        lad.pick()
        lad.note_occupancy(0, 4)
        assert lad.occupancy_ema == pytest.approx(0.8)
        assert lad.pick() == 8
        assert lad.switches == 0

    def test_needs_at_least_one_bucket(self):
        with pytest.raises(ValueError, match="at least one bucket"):
            BucketLadder((), hysteresis_ticks=1, alpha=0.5)


class TestTrafficShaperAdmission:
    def _tick(self, n=1):
        return (DENSE, [(b"\xa5" * 84, 1.0 + 0.001 * k) for k in range(n)])

    def test_bounded_queue_sheds_oldest_with_counters(self):
        sh = TrafficShaper(2, SchedulerConfig(max_backlog_ticks=3))
        first = self._tick(1)
        sh.offer_tick([first, None])
        sh.offer_tick([[self._tick(2), self._tick(3), self._tick(4)], None])
        assert sh.backlog_depths() == [3, 0]
        assert sh.admission_drops == [1, 0] and sh.shed_total == 1
        # the OLDEST tick went: the queue's head is now the second
        assert sh.queues[0][0] is not first
        ticks, _ = sh.drain_plan(0, [0, 1])
        assert len(ticks) == 3

    def test_shed_stream_clears_through_the_ledger(self):
        """The autoscaler's park pre-shed: a whole queue sheds through
        the SAME admission_drops/shed_total counters the oldest-tick
        bound uses — operators watch one ledger."""
        sh = TrafficShaper(2, SchedulerConfig(max_backlog_ticks=8))
        sh.offer_tick([
            [self._tick(1), self._tick(2), self._tick(3)],
            self._tick(4),
        ])
        assert sh.shed_stream(0) == 3
        assert sh.backlog_depths() == [0, 1]
        assert sh.admission_drops == [3, 0] and sh.shed_total == 3
        # an empty queue sheds nothing and leaves the ledger alone
        assert sh.shed_stream(0) == 0
        assert sh.admission_drops == [3, 0] and sh.shed_total == 3

    def test_drain_plan_front_aligns_unequal_queues(self):
        sh = TrafficShaper(3, SchedulerConfig(rungs=(1, 2, 4)))
        sh.offer_tick([[self._tick(1), self._tick(2)], self._tick(3), None])
        ticks, rung = sh.drain_plan(0, [0, 1, None])
        assert rung == 2  # depth 2 -> the 2-rung
        assert len(ticks) == 2
        assert ticks[0][0] is not None and ticks[0][1] is not None
        assert ticks[1][0] is not None and ticks[1][1] is None
        assert ticks[0][2] is None  # stream 2 not on this shard
        assert sh.backlog_depths() == [0, 0, 0]

    def test_drain_plan_empty_still_walks_the_ladder_down(self):
        sh = TrafficShaper(1, SchedulerConfig(
            rungs=(1, 4), hysteresis_ticks=1,
        ))
        sh.offer_tick([[self._tick(1)] * 4])
        _, rung = sh.drain_plan(0, [0])
        assert rung == 4
        _, rung = sh.drain_plan(0, [0])   # empty drain observed
        assert rung == 1

    def test_status_payload_shape(self):
        sh = TrafficShaper(2, SchedulerConfig())
        sh.offer_tick([self._tick(2), None])
        st = sh.status()
        assert st["backlog"] == [1, 0]
        assert st["admission_drops"] == [0, 0]
        assert st["shed_total"] == 0
        assert len(st["byte_rates"]) == 2 and st["byte_rates"][0] > 0
        # the latency model is always in the payload (empty before any
        # seed/drain); the bucket-ladder keys only with bucket_rungs
        assert st["latency_model"] == {}
        assert "active_buckets" not in st
        assert "bucket_switches" not in st


class TestTrafficShaperBucketLadder:
    def _tick(self, n=1):
        return (DENSE, [(b"\xa5" * 84, 1.0 + 0.001 * k) for k in range(n)])

    def _shaper(self, streams=4, **over):
        cfg = dict(
            rungs=(1, 2), hysteresis_ticks=2,
            bucket_rungs=(4, 8), occupancy_alpha=1.0,
        )
        cfg.update(over)
        return TrafficShaper(streams, SchedulerConfig(**cfg))

    def test_disabled_without_bucket_rungs(self):
        sh = TrafficShaper(2, SchedulerConfig())
        assert sh.bucket_ladders is None
        assert sh.bucket_plan(0) is None

    def test_drain_plan_observes_occupancy_and_collapses(self):
        sh = self._shaper()
        assert sh.bucket_plan(0) == 8  # starts at the full-size cap
        # one live lane of four: occupancy 0.25 -> immediate collapse
        sh.offer_tick([self._tick(1), None, None, None])
        ticks, _rung = sh.drain_plan(0, [0, 1, 2, 3])
        assert len(ticks) == 1
        assert sh.bucket_plan(0) == 4
        assert sh.bucket_ladders[0].switches == 1

    def test_empty_drain_still_walks_the_bucket_ladder(self):
        """An all-idle drain observes occupancy 0 — the ladder must see
        the collapse even when nothing dispatches, exactly like the
        rung ladder's empty-drain step-down."""
        sh = self._shaper()
        _ticks, _rung = sh.drain_plan(0, [0, 1, 2, 3])
        assert _ticks == []
        assert sh.bucket_plan(0) == 4

    def test_recovery_is_hysteretic(self):
        sh = self._shaper()
        sh.drain_plan(0, [0, 1, 2, 3])          # collapse to 4
        for pick in (4, 8):                     # 2-drain streak, then up
            sh.offer_tick([self._tick(1)] * 4)
            sh.drain_plan(0, [0, 1, 2, 3])
            assert sh.bucket_plan(0) == pick

    def test_per_shard_ladders_are_independent(self):
        sh = TrafficShaper(4, SchedulerConfig(
            rungs=(1, 2), hysteresis_ticks=2,
            bucket_rungs=(4, 8), occupancy_alpha=1.0,
        ), shards=2)
        # shard 0's lanes idle, shard 1's lanes live
        sh.offer_tick([None, None, self._tick(1), self._tick(1)])
        sh.drain_plan(0, [0, 1])
        sh.drain_plan(1, [2, 3])
        assert sh.bucket_plan(0) == 4
        assert sh.bucket_plan(1) == 8

    def test_status_carries_the_ladder_and_model(self):
        sh = self._shaper()
        sh.model.note(1, 4, 0.002)
        sh.drain_plan(0, [0, 1, 2, 3])          # collapse
        st = sh.status()
        assert st["active_buckets"] == [4]
        assert st["bucket_switches"] == 1
        assert st["latency_model"] == {"T1xM4": 2.0}


# ---------------------------------------------------------------------------
# byte-rate-weighted placement
# ---------------------------------------------------------------------------


class TestWeightedTopology:
    def test_default_weights_are_the_stream_count(self):
        topo = FleetTopology(4, 2, 4)
        assert topo.shard_load(0) == 2.0 == topo.shard_load(1)
        assert topo.weight_of(3) == 1.0

    def test_assign_prefers_the_weighted_cold_shard(self):
        topo = FleetTopology(5, 2, 5)
        # shard 0 hosts {0, 2, 4}, shard 1 hosts {1, 3}: by count the
        # cold shard is 1 — but stream 1 is HOT, so shard 0 is colder
        topo.set_weights({1: 5.0})
        topo.release(4)
        assert topo.assign(4)[0] == 0

    def test_evacuate_places_heaviest_victims_first(self):
        topo = FleetTopology(6, 3, 3)
        # shard 1 hosts {1, 4}: make 4 the hot one
        topo.set_weight(4, 10.0)
        plan = topo.evacuate(1)
        assert [p[0] for p in plan] == [4, 1]
        # the hot victim landed alone; the cold one joined the rest
        dst_hot = plan[0][1]
        assert topo.shard_load(dst_hot) >= 10.0

    def test_rebalance_moves_the_improving_heavy_stream(self):
        topo = FleetTopology(6, 3, 3)
        topo.set_weights({0: 4.0, 3: 1.0})
        topo.evacuate(1)              # strand shard 1's tenants elsewhere
        moves = topo.rebalance_into(1)
        # the balance-improving movers land heaviest-first and every
        # move strictly improves the spread
        weights = [topo.weight_of(m[0]) for m in moves]
        assert weights == sorted(weights, reverse=True)
        loads = [topo.shard_load(s) for s in range(3)]
        assert max(loads) - min(loads) <= max(weights + [1.0])

    def test_rebalance_not_blocked_by_an_unmovable_heavy_shard(self):
        """The most-loaded shard's sole tenant can be too heavy to move
        (load[src] - load[dst] never exceeds w for a single hot
        stream); rebalance must still take improving moves from the
        LIGHTER siblings instead of leaving the re-admitted shard
        empty."""
        topo = FleetTopology(8, 3, 8)
        # shard 0: streams {0, 3, 6}; make 0 a giant, strand the rest
        topo.set_weight(0, 10.0)
        for s in (3, 6):
            topo.release(s)
        for s in (1, 4, 7):   # move shard 1's tenants onto shard 2
            topo.release(s)
        for s in (3, 6, 1, 4, 7):
            topo.assign(s, avoid=(0, 1))
        assert topo.streams_on(1) == []
        moves = topo.rebalance_into(1)
        # the giant never moves (no improvement), but shard 2's
        # weight-1 streams rebalance onto the empty shard
        assert moves and all(m[1] == 2 for m in moves)
        assert len(topo.streams_on(1)) >= 2

    def test_equal_weights_degrade_to_the_original_rule(self):
        a, b = FleetTopology(8, 4, 3), FleetTopology(8, 4, 3)
        b.set_weights([1.0] * 8)
        a.evacuate(1)
        b.evacuate(1)
        assert a.status() == b.status()
        assert a.rebalance_into(1) == b.rebalance_into(1)

    def test_weight_validation(self):
        topo = FleetTopology(2, 1, 2)
        with pytest.raises(IndexError):
            topo.set_weight(7, 1.0)
        topo.set_weight(0, -5.0)       # clamped, never zero/negative
        assert topo.weight_of(0) > 0
        # stream 1 still weighs its default 1.0; the clamped stream 0
        # contributes its (tiny) floor, never a negative load
        assert topo.status()[0]["load"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# engine rung ladder (device work: small geometry)
# ---------------------------------------------------------------------------


def _streams_frames(s, n=64, syncs=(0, 17, 34, 51)):
    return [
        (DENSE, _make_stream(DENSE, n, np.random.default_rng(40 + i),
                             syncs=syncs))
        for i in range(s)
    ]


class TestEngineRungLadder:
    def test_any_rung_is_byte_exact_vs_host(self):
        """One backlog drained at rungs 1, 2 and 4 (fresh engines) is
        byte-identical to the independent host paths every time — the
        rung picks WHEN ticks dispatch, never what they compute."""
        s = 2
        streams_frames = _streams_frames(s)
        ticks = _mk_ticks(
            streams_frames, np.random.default_rng(9), idle_prob=0.0
        )
        host = _host_reference(ticks, s)
        for rung in (1, 2, 4):
            eng = FleetFusedIngest(
                _params(), s, beams=BEAMS, buckets=(4,), max_revs=6,
                rungs=(1, 2, 4),
            )
            outs = eng.submit_backlog(ticks, rung=rung)
            _assert_fleet_outputs_equal(host, outs)

    def test_rung_dispatch_accounting(self):
        s = 2
        ticks = _mk_ticks(
            _streams_frames(s), np.random.default_rng(3), idle_prob=0.0
        )[:7]
        eng = FleetFusedIngest(
            _params(), s, beams=BEAMS, buckets=(4,), max_revs=6,
            rungs=(1, 2, 4),
        )
        eng.submit_backlog(ticks, rung=4)
        # 7 slices at rung 4: one full group of 4, one of 3 (padded
        # super), i.e. 2 super dispatches and nothing at other rungs
        assert eng.rung_dispatches[4] == 2
        assert eng.rung_dispatches[1] == 0
        assert sum(eng.rung_dispatches.values()) == eng.dispatch_count

    def test_unwarmed_rung_refused(self):
        eng = FleetFusedIngest(
            _params(), 1, beams=BEAMS, buckets=(4,), rungs=(1, 2),
        )
        ticks = _mk_ticks(
            _streams_frames(1), np.random.default_rng(1), idle_prob=0.0
        )
        with pytest.raises(ValueError, match="not a warmed rung"):
            eng.submit_backlog(ticks, rung=3)

    def test_ensure_rungs_union_and_late_refusal(self):
        eng = FleetFusedIngest(
            _params(), 1, beams=BEAMS, buckets=(4,), rungs=(1, 2),
        )
        eng.ensure_rungs((1, 4))
        assert eng.rungs == (1, 2, 4)
        ticks = _mk_ticks(
            _streams_frames(1), np.random.default_rng(2), idle_prob=0.0
        )
        eng.submit_backlog(ticks[:1], rung=1)
        eng.ensure_rungs((1, 2))  # subset: fine after traffic
        with pytest.raises(RuntimeError, match="already ticked"):
            eng.ensure_rungs((1, 8))

    def test_ensure_rungs_refused_after_precompile(self):
        """Extending the ladder AFTER precompile would hand out depths
        with no compiled executable behind them — the first deep drain
        would pay its compile inside the serving loop, so the engine
        refuses even before any traffic."""
        eng = FleetFusedIngest(
            _params(), 1, beams=BEAMS, buckets=(4,), rungs=(1, 2),
        )
        eng.precompile([DENSE])
        eng.ensure_rungs((1, 2))  # subset: fine
        with pytest.raises(RuntimeError, match="precompiled"):
            eng.ensure_rungs((1, 4))


# ---------------------------------------------------------------------------
# service serving seam
# ---------------------------------------------------------------------------


def _svc_params(**over):
    base = dict(
        fleet_ingest_backend="fused", sched_rungs=(1, 2, 4),
        admission_max_backlog_ticks=8,
    )
    base.update(over)
    return _params(**base)


class TestServiceServingSeam:
    def test_offer_drain_matches_plain_backlog(self):
        s = 2
        streams_frames = _streams_frames(s)
        ticks = _mk_ticks(
            streams_frames, np.random.default_rng(11), idle_prob=0.0
        )
        # the bound must not bite here: this test is drain parity, the
        # shed policy has its own tests above
        p = _svc_params(admission_max_backlog_ticks=64)
        ref = ShardedFilterService(
            p, s, beams=BEAMS, fleet_ingest_buckets=(4,)
        )
        ref_outs = ref.submit_bytes_backlog(ticks)

        svc = ShardedFilterService(
            p, s, beams=BEAMS, fleet_ingest_buckets=(4,)
        )
        svc.attach_scheduler()
        svc.fleet_ingest.precompile([DENSE] * s)
        # deliver the whole backlog as one burst offer, drain once
        svc.offer_bytes([[t[i] for t in ticks if t[i]] for i in range(s)])
        outs = svc.drain_scheduled()
        assert len(outs) == s
        for i in range(s):
            assert len(outs[i]) == len(ref_outs[i])
            for a, b in zip(outs[i], ref_outs[i]):
                assert np.array_equal(
                    np.asarray(a.ranges), np.asarray(b.ranges)
                )
        # the burst drained above rung 1
        assert any(
            n for r, n in svc.fleet_ingest.rung_dispatches.items()
            if r > 1
        )
        st = svc.scheduler_status()
        assert st["backlog"] == [0] * s
        assert st["rung_dispatches"] == dict(
            svc.fleet_ingest.rung_dispatches
        )

    def test_warmup_seeds_the_latency_model(self):
        """Precompile's timed warmup re-runs land in
        ``FleetFusedIngest.warmup_costs``; the first scheduled drain
        folds them into the shared LatencyModel (and clears the engine
        stash), so EVERY warmed (rung, bucket) executable is priced
        before any live traffic reaches the deadline cap."""
        svc = ShardedFilterService(
            _svc_params(), 2, beams=BEAMS, fleet_ingest_buckets=(4, 8)
        )
        svc.attach_scheduler()
        svc.fleet_ingest.precompile([DENSE] * 2)
        warmed = set(svc.fleet_ingest.warmup_costs)
        assert warmed == {
            (r, b) for r in (1, 2, 4) for b in (4, 8)
        }
        svc.drain_scheduled()   # even an empty drain consumes the seeds
        assert svc.fleet_ingest.warmup_costs == {}
        assert set(svc.scheduler.model.table_ms()) == {
            f"T{r}xM{b}" for r in (1, 2, 4) for b in (4, 8)
        }

    def test_scheduler_status_carries_the_staging_counters(self):
        svc = ShardedFilterService(
            _svc_params(), 2, beams=BEAMS, fleet_ingest_buckets=(4,)
        )
        svc.attach_scheduler()
        st = svc.scheduler_status()
        assert st["rung_bucket_dispatches"] == {}
        assert st["staging_overlap_hits"] == 0
        assert st["latency_model"] == {}

    def test_quarantine_checkpoint_deferral_gate(self):
        """With ``_defer_checkpoints`` armed (a double-buffered drain
        in flight), a quarantine hook queues the stream instead of
        pulling the checkpoint inline; disarmed, the same call freezes
        state immediately — the overlap hook replays the queue through
        this exact path."""
        svc = ShardedFilterService(
            _svc_params(), 2, beams=BEAMS, fleet_ingest_buckets=(4,)
        )
        svc._ensure_byte_ingest()
        svc.fleet_ingest.precompile([DENSE] * 2)
        svc._defer_checkpoints = []
        svc._quarantine_stream(0)
        assert svc._defer_checkpoints == [0]
        assert not svc.stream_checkpoints and svc.quarantines == 0
        svc._defer_checkpoints = None
        svc._quarantine_stream(0)
        assert 0 in svc.stream_checkpoints and svc.quarantines == 1

    def test_host_backend_refuses_scheduler_and_rung(self):
        svc = ShardedFilterService(
            _params(fleet_ingest_backend="host"), 2, beams=BEAMS
        )
        with pytest.raises(ValueError, match="fused"):
            svc.attach_scheduler()
        with pytest.raises(ValueError, match="rung"):
            svc.submit_bytes_backlog([[None, None]], rung=2)
        with pytest.raises(ValueError, match="fused"):
            svc.submit_bytes_backlog(
                [[None, None]], overlap_work=lambda: None
            )

    def test_offer_requires_attach(self):
        svc = ShardedFilterService(
            _svc_params(), 2, beams=BEAMS, fleet_ingest_buckets=(4,)
        )
        with pytest.raises(RuntimeError, match="attach_scheduler"):
            svc.offer_bytes([None, None])


# ---------------------------------------------------------------------------
# /diagnostics scheduler value group (pinned like shard_topology)
# ---------------------------------------------------------------------------


class TestSchedulerDiagnostics:
    def test_rendering_pinned(self):
        from rplidar_ros2_driver_tpu.node.diagnostics import (
            DiagnosticsUpdater,
        )
        from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState
        from rplidar_ros2_driver_tpu.node.publisher import (
            CollectingPublisher,
        )

        payload = {
            "rungs": [4, 1],
            "backlog": [3, 0, 1],
            "admission_drops": [2, 0, 0],
            "shed_total": 2,
            "byte_rates": [512.5, 0.0, 33.1],
            "rung_dispatches": {1: 7, 4: 2},
            "weights": [2.0, 1.0, 1.25],
        }
        status = DiagnosticsUpdater("rig", CollectingPublisher()).update(
            lifecycle=LifecycleState.ACTIVE, fsm_state=None,
            port="pod", rpm=0, device_info="",
            scheduler=payload,
        )
        assert status.values["Sched Rung"] == "4,1"
        assert status.values["Sched Backlog"] == "3,0,1"
        assert status.values["Admission Drops"] == "2,0,0"
        assert status.values["Admission Shed Total"] == "2"
        assert status.values["Rung Dispatches"] == "T1:7 T4:2"
        assert status.values["Placement Weights"] == "2.00,1.00,1.25"
        # the link-latency-hiding keys are absent from a pre-PR-16
        # payload, so their value rows must be too
        for key in ("Latency Model ms", "Active Bucket",
                    "Bucket Switches", "Staging Overlap Hits"):
            assert key not in status.values

    def test_rendering_pinned_latency_model_group(self):
        from rplidar_ros2_driver_tpu.node.diagnostics import (
            DiagnosticsUpdater,
        )
        from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState
        from rplidar_ros2_driver_tpu.node.publisher import (
            CollectingPublisher,
        )

        payload = {
            "rungs": [2],
            "backlog": [0, 1],
            "admission_drops": [0, 0],
            "shed_total": 0,
            "byte_rates": [10.0, 0.0],
            "rung_dispatches": {1: 3},
            "latency_model": {"T1xM4": 0.5, "T1xM8": 0.9, "T2xM4": 0.8},
            "active_buckets": [4, 8],
            "bucket_switches": 3,
            "staging_overlap_hits": 17,
        }
        status = DiagnosticsUpdater("rig", CollectingPublisher()).update(
            lifecycle=LifecycleState.ACTIVE, fsm_state=None,
            port="pod", rpm=0, device_info="",
            scheduler=payload,
        )
        # keys sort lexicographically: T1xM4 < T1xM8 < T2xM4
        assert status.values["Latency Model ms"] == (
            "T1xM4:0.5 T1xM8:0.9 T2xM4:0.8"
        )
        assert status.values["Active Bucket"] == "4,8"
        assert status.values["Bucket Switches"] == "3"
        assert status.values["Staging Overlap Hits"] == "17"

    def test_live_payload_feeds_the_renderer(self):
        from rplidar_ros2_driver_tpu.node.diagnostics import (
            DiagnosticsUpdater,
        )
        from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState
        from rplidar_ros2_driver_tpu.node.publisher import (
            CollectingPublisher,
        )

        svc = ShardedFilterService(
            _svc_params(), 2, beams=BEAMS, fleet_ingest_buckets=(4,)
        )
        svc.attach_scheduler()
        status = DiagnosticsUpdater("rig", CollectingPublisher()).update(
            lifecycle=LifecycleState.ACTIVE, fsm_state=None,
            port="svc", rpm=0, device_info="",
            scheduler=svc.scheduler_status(),
        )
        assert status.values["Sched Backlog"] == "0,0"
        assert "Rung Dispatches" in status.values


# ---------------------------------------------------------------------------
# pod-of-pods: steal planning, the autoscaler, the byte-equal pin
# ---------------------------------------------------------------------------


class TestStealPlanning:
    def _tick(self, n=1):
        return (DENSE, [(b"\xa5" * 84, 1.0 + 0.001 * k) for k in range(n)])

    def _shaper(self, streams, shards, **over):
        over.setdefault("steal_threshold_ticks", 2)
        cfg = SchedulerConfig(rungs=(1, 2, 4), **over)
        return TrafficShaper(streams, cfg, shards=shards)

    def test_predict_drain_s_prices_with_the_model(self):
        sh = self._shaper(2, 2)
        assert sh.predict_drain_s(0, 0) == 0.0
        # an unpriced shard has no headroom EVIDENCE
        assert sh.predict_drain_s(0, 3) is None
        sh.model.seed(4, 8, 0.002)
        # depth 3 targets the 4-rung: one dispatch
        assert sh.predict_drain_s(0, 3) == pytest.approx(0.002)
        # depth 9 at the top rung: ceil(9/4) = 3 dispatches
        assert sh.predict_drain_s(0, 9) == pytest.approx(0.006)

    def test_threshold_gates_the_phase(self):
        sh = self._shaper(2, 2, steal_threshold_ticks=0)
        sh.offer_tick([[self._tick()] * 6, None])
        assert sh.plan_steals({0: [0], 1: [1]}, {0: 1, 1: 1}) == {}
        sh = self._shaper(2, 2)
        sh.offer_tick([[self._tick()] * 2, None])   # == thr, not past it
        assert sh.plan_steals({0: [0], 1: [1]}, {0: 1, 1: 1}) == {}
        assert sh.steals == 0 and sh.steal_log == []

    def test_deep_donor_steals_to_the_idle_sibling(self):
        sh = self._shaper(4, 2)
        sh.offer_tick([[self._tick()] * 4, None, self._tick(), None])
        plan = sh.plan_steals({0: [0, 1], 1: [2, 3]}, {0: 1, 1: 1})
        assert plan == {1: [(0, 0)]}
        # the accounting identity the bench asserts, from tick one
        assert sh.steals == 1 and sh.steal_ticks == 4
        assert sh.steal_log == [(1, 0, 0, 4)]
        assert sh.steal_ticks == sum(n for *_, n in sh.steal_log)

    def test_taker_needs_an_idle_lane(self):
        sh = self._shaper(2, 2)
        sh.offer_tick([[self._tick()] * 4, None])
        assert sh.plan_steals({0: [0], 1: [1]}, {0: 0, 1: 0}) == {}

    def test_deep_takers_are_disqualified(self):
        sh = self._shaper(2, 2)
        sh.offer_tick([[self._tick()] * 5, [self._tick()] * 4])
        # both shards past the threshold: donors, never takers
        assert sh.plan_steals({0: [0], 1: [1]}, {0: 1, 1: 1}) == {}

    def test_shallowest_qualifying_taker_wins(self):
        sh = self._shaper(3, 3)
        sh.offer_tick([[self._tick()] * 5, self._tick(), None])
        plan = sh.plan_steals(
            {0: [0], 1: [1], 2: [2]}, {0: 1, 1: 1, 2: 1}
        )
        assert plan == {2: [(0, 0)]}

    def test_donor_donates_deepest_until_the_threshold(self):
        sh = self._shaper(5, 3)
        sh.offer_tick([
            [self._tick()] * 5, [self._tick()] * 4, [self._tick()] * 3,
            None, None,
        ])
        plan = sh.plan_steals(
            {0: [0, 1, 2], 1: [3], 2: [4]}, {0: 0, 1: 2, 2: 2}
        )
        moved = [s for takes in plan.values() for s, _src in takes]
        assert sorted(moved) == [0, 1, 2]   # depth sank to the thr
        assert sh.steal_ticks == 12
        # a borrow deepens a taker: the planner spreads, deepest first
        assert plan == {1: [(0, 0)], 2: [(1, 0), (2, 0)]}

    def test_headroom_budget_vetoes_unpriced_takers(self):
        sh = self._shaper(2, 2, steal_headroom_ms=5.0)
        sh.offer_tick([[self._tick()] * 4, None])
        # no model entry: the planner refuses to gamble the deadline
        assert sh.plan_steals({0: [0], 1: [1]}, {0: 1, 1: 1}) == {}
        sh.model.seed(4, 8, 0.001)   # 1 ms/dispatch fits the 5 ms budget
        plan = sh.plan_steals({0: [0], 1: [1]}, {0: 1, 1: 1})
        assert plan == {1: [(0, 0)]}

    def test_headroom_budget_vetoes_overpriced_takers(self):
        sh = self._shaper(2, 2, steal_headroom_ms=5.0)
        sh.model.seed(4, 8, 0.010)   # 10 ms/dispatch blows the budget
        sh.offer_tick([[self._tick()] * 4, None])
        assert sh.plan_steals({0: [0], 1: [1]}, {0: 1, 1: 1}) == {}

    def test_deadline_reserve_is_the_budget(self):
        # with a deadline, the budget is deadline - headroom: 2 ms
        sh = self._shaper(
            2, 2, deadline_ms=3.0, steal_headroom_ms=1.0
        )
        sh.model.seed(4, 8, 0.0025)
        sh.offer_tick([[self._tick()] * 4, None])
        assert sh.plan_steals({0: [0], 1: [1]}, {0: 1, 1: 1}) == {}
        sh2 = self._shaper(
            2, 2, deadline_ms=3.0, steal_headroom_ms=1.0
        )
        sh2.model.seed(4, 8, 0.0015)
        sh2.offer_tick([[self._tick()] * 4, None])
        assert sh2.plan_steals({0: [0], 1: [1]}, {0: 1, 1: 1}) == {
            1: [(0, 0)]
        }

    def test_status_carries_the_steal_counters(self):
        sh = self._shaper(2, 2)
        st = sh.status()
        assert st["steals"] == 0 and st["steal_ticks"] == 0
        sh.offer_tick([[self._tick()] * 4, None])
        sh.plan_steals({0: [0], 1: [1]}, {0: 1, 1: 1})
        st = sh.status()
        assert st["steals"] == 1 and st["steal_ticks"] == 4


class TestPodAutoscalerPolicy:
    def _auto(self, **over):
        from rplidar_ros2_driver_tpu.parallel.scheduler import (
            PodAutoscaler,
        )

        cfg = SchedulerConfig(
            autoscale_enable=True, autoscale_low_watermark=0.25,
            autoscale_high_watermark=0.75,
            autoscale_hysteresis_ticks=3, autoscale_rate_floor=256.0,
            **over,
        )
        return PodAutoscaler(cfg, lanes=2)

    def test_liveness_floor(self):
        auto = self._auto()
        assert auto.live_streams([0.0, 100.0, 256.0, 1024.0]) == 2

    def test_thin_streak_fires_down_after_hysteresis(self):
        auto = self._auto()
        quiet = [0.0, 0.0, 0.0, 0.0]
        assert auto.note_tick(quiet, 2) is None
        assert auto.state == "thin 1/3"
        assert auto.note_tick(quiet, 2) is None
        assert auto.note_tick(quiet, 2) == "down"
        assert auto.scale_downs == 1
        # the streak reset: the next decision needs a fresh streak
        assert auto.note_tick(quiet, 2) is None

    def test_pressure_streak_fires_up(self):
        auto = self._auto()
        hot = [1000.0] * 4
        for _ in range(2):
            assert auto.note_tick(hot, 2) is None
        assert auto.note_tick(hot, 2) == "up"
        assert auto.scale_ups == 1

    def test_dead_zone_resets_both_streaks(self):
        auto = self._auto()
        quiet, mid = [0.0] * 4, [1000.0, 1000.0, 0.0, 0.0]
        auto.note_tick(quiet, 2)
        auto.note_tick(quiet, 2)
        # occupancy 2/4 sits in the watermark gap: a sawtooth that
        # recrosses the band restarts the count
        assert auto.note_tick(mid, 2) is None
        assert auto.state == "steady"
        assert auto.note_tick(quiet, 2) is None
        assert auto.note_tick(quiet, 2) is None
        assert auto.note_tick(quiet, 2) == "down"

    def test_gated_side_still_ticks_its_streak(self):
        auto = self._auto()
        quiet = [0.0] * 4
        for _ in range(4):
            assert auto.note_tick(quiet, 2, can_down=False) is None
        assert auto.scale_downs == 0
        # the decision lands the moment the gate opens
        assert auto.note_tick(quiet, 2, can_down=True) == "down"

    def test_occupancy_uses_the_active_fleet_capacity(self):
        auto = self._auto()
        hot = [1000.0, 1000.0, 1000.0, 0.0]
        # 3 live / (2 shards * 2 lanes) = 0.75: the dead zone
        assert auto.note_tick(hot, 2) is None
        assert auto.state == "steady"
        # a parked fleet halves the capacity: 3/2 caps at 1.0 > high
        auto.note_tick(hot, 1)
        assert auto.state == "pressure 1/3"
        assert auto.occupancy == 1.0

    def test_status_payload(self):
        auto = self._auto()
        st = auto.status()
        assert st == {
            "state": "steady", "occupancy": None,
            "scale_downs": 0, "scale_ups": 0,
        }
        auto.note_tick([0.0] * 4, 2)
        st = auto.status()
        assert st["state"] == "thin 1/3" and st["occupancy"] == 0.0


class TestPodStealByteEqual:
    def test_steal_schedule_is_byte_equal_to_no_steal(self):
        """The acceptance pin: a skewed trace forces cross-shard
        steals, and the stolen schedule's per-stream outputs are
        byte-identical to the static pod's — the steal policy picks
        WHERE a queue drains, never what (the bench asserts the same
        at config-21 scale; this is the tier-1 unit)."""
        from test_chaos import _fleet_ticks, _map_params
        from rplidar_ros2_driver_tpu.parallel.service import (
            ElasticFleetService,
        )

        streams, shards = 4, 2
        ticks = _fleet_ticks(streams, 24)

        def build(steal):
            params = _map_params(
                fleet_ingest_backend="fused", map_backend="fused",
                shard_count=shards, failover_snapshot_ticks=4,
                shard_starvation_ticks=500,
                sched_rungs=(1, 2, 4),
                steal_threshold_ticks=2 if steal else 0,
            )
            pod = ElasticFleetService(
                params, streams, shards=shards, beams=BEAMS,
                fleet_ingest_buckets=(8,),
            )
            pod.attach_scheduler()
            pod.precompile([DENSE])
            return pod

        pods = {"static": build(False), "pod": build(True)}
        deep = [
            s for s in pods["pod"].topology.lane_streams(0)
            if s is not None
        ][:2]
        cursor = [0] * streams

        def take(i, n):
            got = [
                ticks[t][i]
                for t in range(cursor[i], min(cursor[i] + n, len(ticks)))
            ]
            cursor[i] += len(got)
            return [g for g in got if g] or None

        outs = {n: [[] for _ in range(streams)] for n in pods}
        for t in range(5):
            items = [
                take(i, 4 if i in deep else 1) for i in range(streams)
            ]
            for name in (
                ("static", "pod") if t % 2 == 0 else ("pod", "static")
            ):
                pods[name].offer_bytes(items)
                for i, g in enumerate(pods[name].drain_scheduled()):
                    outs[name][i].extend(g)
        pp = pods["pod"]
        assert pp.scheduler.steals > 0
        assert pp.steal_drops == 0
        assert pp.scheduler.steal_ticks == sum(
            n for *_, n in pp.scheduler.steal_log
        )
        assert pods["static"].scheduler.steals == 0
        for i in range(streams):
            a, b = outs["pod"][i], outs["static"][i]
            assert len(a) == len(b) and len(a) > 0
            for x, y in zip(a, b):
                assert np.array_equal(
                    np.asarray(x.ranges), np.asarray(y.ranges)
                )
                assert np.array_equal(
                    np.asarray(x.voxel), np.asarray(y.voxel)
                )


class TestPodDiagnostics:
    def _update(self, pod_payload):
        from rplidar_ros2_driver_tpu.node.diagnostics import (
            DiagnosticsUpdater,
        )
        from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState
        from rplidar_ros2_driver_tpu.node.publisher import (
            CollectingPublisher,
        )

        return DiagnosticsUpdater("rig", CollectingPublisher()).update(
            lifecycle=LifecycleState.ACTIVE, fsm_state=None,
            port="pod", rpm=0, device_info="",
            pod=pod_payload,
        )

    def test_rendering_pinned(self):
        status = self._update({
            "hosts": 2,
            "per_host": [
                {"host": 0, "shards": [
                    {"shard": 0, "state": "UP", "streams": 3},
                    {"shard": 1, "state": "PARKED", "streams": 0},
                ]},
                {"host": 1, "shards": [
                    {"shard": 2, "state": "UP", "streams": 3},
                ]},
            ],
            "parked": [1],
            "steals": 12,
            "steal_ticks": 48,
            "steal_drops": 0,
            "scale_downs": 1,
            "scale_ups": 1,
            "autoscaler": {
                "state": "thin 2/3", "occupancy": 0.167,
                "scale_downs": 1, "scale_ups": 0,
            },
        })
        assert status.values["Pod Host 0"] == "0:UP[3] 1:PARKED[0]"
        assert status.values["Pod Host 1"] == "2:UP[3]"
        assert status.values["Steals"] == "12"
        assert status.values["Steal Ticks"] == "48"
        assert status.values["Scale-Downs"] == "1"
        assert status.values["Scale-Ups"] == "1"
        assert status.values["Autoscaler"] == "thin 2/3 (occ 0.167)"

    def test_group_absent_without_payload(self):
        status = self._update(None)
        for key in ("Pod Host 0", "Steals", "Steal Ticks",
                    "Scale-Downs", "Scale-Ups", "Autoscaler"):
            assert key not in status.values

    def test_no_autoscaler_row_without_the_policy(self):
        status = self._update({
            "hosts": 1,
            "per_host": [{"host": 0, "shards": []}],
            "steals": 0, "steal_ticks": 0,
            "scale_downs": 0, "scale_ups": 0,
            "autoscaler": None,
        })
        assert status.values["Pod Host 0"] == "n/a"
        assert "Autoscaler" not in status.values

    def test_live_payload_feeds_the_renderer(self):
        from test_chaos import _map_params
        from rplidar_ros2_driver_tpu.parallel.service import (
            ElasticFleetService,
        )

        params = _map_params(
            fleet_ingest_backend="fused", map_backend="fused",
            shard_count=2, steal_threshold_ticks=2,
            autoscale_enable=True,
        )
        pod = ElasticFleetService(
            params, 4, shards=2, beams=BEAMS,
            fleet_ingest_buckets=(4,),
        )
        pod.attach_scheduler()
        status = self._update(pod.pod_status())
        assert "Pod Host 0" in status.values
        assert status.values["Steals"] == "0"
        assert status.values["Autoscaler"].startswith("steady")


class TestAutoscaleParkShed:
    def _tick(self, n=1):
        return (DENSE, [(b"\xa5" * 84, 1.0 + 0.001 * k) for k in range(n)])

    def test_park_pre_sheds_stranded_backlog_then_unpark_restores(self):
        """The autoscale-aware admission cycle: a scale-down past
        full-coverage capacity must not silently strand queued ticks on
        the parked engine.  The FIRST park the survivors can absorb
        moves every row live and leaves the ledger untouched; a SECOND
        park (capacity now below coverage — the live-stream relaxation)
        pre-sheds each stranded stream's backlog through the shaper's
        admission ledger (``park_sheds`` mirrors the total pod-side)
        and snapshots the live row, and the scale-up rebalance restores
        the stream from that snapshot — park -> shed -> unpark, fully
        accounted."""
        from test_chaos import _map_params
        from rplidar_ros2_driver_tpu.parallel.service import (
            ElasticFleetService,
        )

        streams, shards = 6, 3
        params = _map_params(
            fleet_ingest_backend="fused", map_backend="fused",
            shard_count=shards, failover_snapshot_ticks=4,
            shard_starvation_ticks=500, sched_rungs=(1, 2),
            autoscale_enable=True,
        )
        pod = ElasticFleetService(
            params, streams, shards=shards, beams=BEAMS,
            fleet_ingest_buckets=(8,),
        )
        pod.attach_scheduler()
        pod.precompile([DENSE])
        for _ in range(2):      # live rows everywhere
            pod.offer_bytes([self._tick()] * streams)
            pod.drain_scheduled()
        # first park: the survivors' idle lanes absorb every evacuee
        pod._park_shard(2)
        assert pod.park_sheds == 0
        assert pod.scheduler.shed_total == 0
        assert pod.topology.unhosted() == []
        assert not [e for e in pod.events if e[1] == "park_shed"]
        # second park: the survivors are full — every hosted stream
        # strands, with queued backlog the park must not silently drop
        victim = 1
        stranded = sorted(pod.topology.streams_on(victim))
        assert stranded
        pod.offer_bytes([self._tick()] * streams)
        depth = {s: len(pod.scheduler.queues[s]) for s in stranded}
        assert all(d > 0 for d in depth.values())
        drops_before = list(pod.scheduler.admission_drops)
        pod._park_shard(victim)
        assert pod.park_sheds == sum(depth.values()) > 0
        assert pod.pod_status()["park_sheds"] == pod.park_sheds
        assert pod.scheduler.shed_total >= pod.park_sheds
        for s in stranded:
            assert len(pod.scheduler.queues[s]) == 0
            assert (
                pod.scheduler.admission_drops[s]
                == drops_before[s] + depth[s]
            )
            assert s in pod._snap    # the live row snapshotted
        shed_events = [e for e in pod.events if e[1] == "park_shed"]
        assert {e[2] for e in shed_events} == set(stranded)
        assert sorted(pod.topology.unhosted()) == stranded
        assert pod.streams_lost_unhosted == len(stranded)
        assert pod.pod_status()["parked"] == [1, 2]
        # unpark: the rebalance re-homes the stranded streams from
        # their snapshots (the src < 0 restore path)
        pod._unpark_shard(victim)
        assert pod.topology.unhosted() == []
        assert pod.streams_lost_unhosted == 0
        assert pod.pod_status()["parked"] == [2]
        # the restored fleet keeps serving
        pod.offer_bytes([self._tick()] * streams)
        outs = pod.drain_scheduled()
        assert len(outs) == streams


# The zero-recompile / zero-implicit-transfer pin for mid-run rung
# switches lives with the other engine steady-state sentinels in
# tests/test_guards.py (TestAdaptiveRungSteadyState); the pod-of-pods
# analogs (steals + the autoscale cycle) are TestPodScaleoutSteadyState
# there.
