"""One-dispatch stack (PR 13) — fused mapping route parity suite.

Pins the contract that lets the SLAM front-end ride the ingest carry
(``fused_mapping_backend='fused'``: MapState threaded as a donated
``lax.scan`` carry through ops/ingest, the match+update inside the one
compiled program per super-tick per shard):

  * the in-program mapping path is BYTE-EQUAL to the two-dispatch host
    route — ranges, per-tick poses/scores/revisions, final MapState —
    over T∈{1,2,8} super-ticks x fleet 1/3/8 x both matcher backends
    (int32 datapath end to end, so equality is byte-level);
  * T ticks of ingest+mapping collapse from T+T dispatches to
    ceil(T/super_tick_max) — with ZERO separate mapper dispatches;
  * an all-idle fused-mapping tick does not republish stale poses
    (the PR 10 ``last_poses`` fix, extended to the in-program path);
  * the map rows ride the per-stream failover transport from the new
    carry layout (ingest snapshot v3), version bump rejected on skew,
    and the carried map checkpoint format interoperates with
    FleetMapper's byte-for-byte;
  * a mid-backlog format switch resets decode (and the sub-sweep ring)
    without perturbing the carried map — both routes agree;
  * snapshot/restore mid super-tick continues bit-exactly.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.ops import wire
from rplidar_ros2_driver_tpu.protocol.constants import Ans

BEAMS = 256
DENSE = int(Ans.MEASUREMENT_DENSE_CAPSULED)


def _params(route="fused", **over):
    base = dict(
        filter_backend="cpu",
        filter_chain=("clip", "median", "voxel"),
        filter_window=4,
        voxel_grid_size=16,
        fleet_ingest_backend="fused",
        deskew_enable=True,
        sweep_reconstruct_window=3,
        deskew_profile_beams=64,
        deskew_shift_window=4,
        map_enable=True,
        map_backend="host",
        fused_mapping_backend=route,
        map_grid=32,
        map_cell_m=0.2,
    )
    base.update(over)
    return DriverParams(**base)


def _dense_frames(revs: int, ppr: int = 400, drift_per_rev: float = 40.0,
                  seed: int = 0):
    """Dense-capsule wire stream with radial drift (a moving platform,
    so the de-skew estimator and the matcher both do real work)."""
    rng = np.random.default_rng(seed)
    frames = []
    idx = 0
    first = True
    while idx < revs * ppr:
        theta = 360.0 * (idx % ppr) / ppr
        pts = (np.arange(40) + idx) % ppr
        dists = (
            2000.0 + 500.0 * np.sin(2 * np.pi * pts / ppr)
            + drift_per_rev * (idx / ppr)
            + rng.uniform(0.0, 0.25)
        )
        frames.append(wire.encode_dense_capsule(
            int(theta * 64) & 0x7FFF, first, dists.astype(int)
        ))
        idx += 40
        first = False
    return frames


def _byte_ticks(frames, streams: int, run: int = 4, t0: float = 100.0,
                ans: int = DENSE):
    """Per-stream byte ticks (every stream the same frames on its own
    stamp lane — the bench's paced-scene discipline)."""
    ticks = []
    t = [t0 + 5.0 * s for s in range(streams)]
    for i in range(0, len(frames), run):
        tick = []
        for s in range(streams):
            batch = []
            for f in frames[i : i + run]:
                t[s] += 1.25e-3
                batch.append((f, t[s]))
            tick.append((ans, batch))
        ticks.append(tick)
    return ticks


def _build(route, streams, match_backend="xla", super_tick_max=1, **over):
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
    from rplidar_ros2_driver_tpu.parallel.sharding import make_mesh

    params = _params(
        route, match_backend=match_backend,
        super_tick_max=super_tick_max, **over,
    )
    svc = ShardedFilterService(
        params, streams, mesh=make_mesh(1), beams=BEAMS, capacity=1024,
        fleet_ingest_buckets=(4,),
    )
    svc._ensure_byte_ingest()
    svc.attach_mapper()
    return svc


def _pose_row(svc):
    return [
        None if p is None
        else (tuple(int(v) for v in p.pose_q), p.score,
              p.matched_points, p.revision)
        for p in svc.last_poses
    ]


def _map_snap(svc):
    return svc.mapper.snapshot()


def _assert_maps_equal(a, b):
    for k in ("log_odds", "pose", "origin_xy", "revision"):
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


# ---------------------------------------------------------------------------
# ops-level parity: super-tick in-program mapping vs the per-tick host
# mapper golden, the full T x fleet cross
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fleet", [1, 3, 8])
@pytest.mark.parametrize("T", [1, 2, 8])
def test_ops_super_tick_mapping_vs_host_golden(T, fleet):
    """The tentpole claim at the ops layer: a T-tick super-step with
    cfg.mapping lands byte-identical map wires and final MapState to T
    per-tick dispatches WITHOUT mapping whose reconstructed sweeps feed
    the NumPy host mapper (ops/scan_match_ref) tick by tick — the exact
    two-dispatch route the fusion replaces."""
    import jax.numpy as jnp

    from rplidar_ros2_driver_tpu.filters.chain import config_from_params
    from rplidar_ros2_driver_tpu.mapping.mapper import map_config_from_params
    from rplidar_ros2_driver_tpu.ops.deskew import deskew_config_from_params
    from rplidar_ros2_driver_tpu.ops.ingest import (
        create_fleet_ingest_state,
        fleet_aux_len,
        fleet_ingest_config_for,
        super_fleet_ingest_step,
        unpack_super_fleet_ingest_result,
    )
    from rplidar_ros2_driver_tpu.ops.scan_match_ref import map_match_step_np
    from rplidar_ros2_driver_tpu.protocol import timing as timingmod

    params = _params()
    fcfg = config_from_params(params, BEAMS, platform="cpu")
    dsk = deskew_config_from_params(params, BEAMS)
    mcfg = map_config_from_params(params, BEAMS)
    run = 4
    frames = _dense_frames(3, seed=T * 10 + fleet)
    chunks = [frames[i : i + run] for i in range(0, len(frames), run)]
    # pad the chunk list to a T multiple with idle ticks
    while len(chunks) % T:
        chunks.append([])

    def staging(chunk_group, t_clock):
        fb = cfg_map.frame_bytes
        buf = np.zeros((T, fleet, run, fb), np.uint8)
        aux = np.zeros((T, fleet, fleet_aux_len(run)), np.float32)
        for t, ch in enumerate(chunk_group):
            m = len(ch)
            for s in range(fleet):
                if m:
                    buf[t, s, :m, :] = np.frombuffer(
                        b"".join(ch), np.uint8
                    ).reshape(m, -1)
                stamps = [t_clock[s] + 1.25e-3 * (j + 1) for j in range(m)]
                if m:
                    base = stamps[0]
                    aux[t, s, :m] = [x - base for x in stamps]
                    aux[t, s, 2 * run] = (
                        0.0 if prev_base[s] is None else prev_base[s] - base
                    )
                    aux[t, s, 2 * run + 1] = m
                    prev_base[s] = base
                    t_clock[s] = stamps[-1]
        return buf, aux

    cfg_map = fleet_ingest_config_for(
        (DENSE,), timingmod.TimingDesc(), fcfg,
        max_nodes=1024, deskew=dsk, mapping=mcfg,
    )
    cfg_plain = dataclasses.replace(cfg_map, mapping=None)

    # fused arm: T-tick super-steps with in-program mapping
    prev_base = [None] * fleet
    t_clock = [100.0 + 5 * s for s in range(fleet)]
    st = create_fleet_ingest_state(cfg_map, fleet)
    fused_wires = []
    for g in range(0, len(chunks), T):
        buf, aux = staging(chunks[g : g + T], t_clock)
        st, *res = super_fleet_ingest_step(
            st, jnp.asarray(buf), jnp.asarray(aux), cfg=cfg_map
        )
        for tick_rows in unpack_super_fleet_ingest_result(res, cfg_map):
            fused_wires.append([r.map_wire.copy() for r in tick_rows])

    # host arm: the same T-grouped staging through the mapping-less
    # program (identical byte/aux planes), recon planes into the NumPy
    # mapper per tick — the separate-dispatch route
    prev_base = [None] * fleet
    t_clock = [100.0 + 5 * s for s in range(fleet)]
    st_h = create_fleet_ingest_state(cfg_plain, fleet)
    g = mcfg.grid
    host_states = [
        {
            "log_odds": np.zeros((g, g), np.int32),
            "pose": np.zeros((3,), np.int32),
            "origin_xy": np.zeros((2,), np.float32),
            "revision": np.zeros((), np.int32),
        }
        for _ in range(fleet)
    ]
    host_wires = []
    for g in range(0, len(chunks), T):
        buf, aux = staging(chunks[g : g + T], t_clock)
        st_h, *res = super_fleet_ingest_step(
            st_h, jnp.asarray(buf), jnp.asarray(aux), cfg=cfg_plain
        )
        for tick_rows in unpack_super_fleet_ingest_result(res, cfg_plain):
            row_wires = []
            for i, r in enumerate(tick_rows):
                live = 1 if r.recon_pushed else 0
                if live:
                    pts = r.recon_pts
                    new, w5 = map_match_step_np(
                        host_states[i], pts[:, :2].astype(np.float32),
                        pts[:, 2] > 0.5, 1, mcfg,
                    )
                    host_states[i] = new
                else:
                    w5 = np.concatenate([
                        host_states[i]["pose"], [0], [0]
                    ]).astype(np.int32)
                row_wires.append(np.concatenate(
                    [[live], w5, [host_states[i]["revision"]]]
                ).astype(np.int32))
            host_wires.append(row_wires)

    assert len(fused_wires) == len(host_wires)
    for t, (fw, hw) in enumerate(zip(fused_wires, host_wires)):
        for i in range(fleet):
            # idle ticks: the host golden's wire repeats the held pose,
            # the fused wire likewise carries the untouched state —
            # compare whole wires either way
            np.testing.assert_array_equal(fw[i], hw[i], err_msg=f"t={t} s={i}")
    for i in range(fleet):
        np.testing.assert_array_equal(
            np.asarray(st.map_log_odds)[i], host_states[i]["log_odds"]
        )
        np.testing.assert_array_equal(
            np.asarray(st.map_pose)[i], host_states[i]["pose"]
        )


# ---------------------------------------------------------------------------
# service-level route parity (both matcher backends)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("match_backend", ["xla", "pallas"])
def test_service_route_parity(match_backend):
    """Host route vs fused route through ShardedFilterService, tick by
    tick: outputs, per-tick poses and final maps byte-equal; the fused
    route issues ZERO mapper dispatches."""
    streams = 3
    h = _build("host", streams, match_backend)
    f = _build("fused", streams, match_backend)
    ticks = _byte_ticks(_dense_frames(3), streams)
    for t in ticks:
        rh = h.submit_bytes(t)
        rf = f.submit_bytes(t)
        for i in range(streams):
            assert (rh[i] is None) == (rf[i] is None)
            if rh[i] is not None:
                np.testing.assert_array_equal(
                    np.asarray(rh[i].ranges), np.asarray(rf[i].ranges)
                )
        assert _pose_row(h) == _pose_row(f)
    _assert_maps_equal(_map_snap(h), _map_snap(f))
    assert f.mapper.dispatch_count == 0
    assert h.mapper.ticks > 0 and f.mapper.ticks > 0


def test_backlog_drain_dispatch_collapse():
    """T ticks of ingest+mapping in ceil(T/super_tick_max) compiled
    dispatches — mapping included, no separate mapper dispatch — with
    the final map byte-equal to the per-tick host route."""
    streams, T = 3, 4
    h = _build("host", streams)
    f = _build("fused", streams, super_tick_max=T)
    ticks = _byte_ticks(_dense_frames(3), streams)
    for t in ticks:
        h.submit_bytes(t)
    d0 = f.fleet_ingest.dispatch_count
    f.submit_bytes_backlog(ticks)
    got = f.fleet_ingest.dispatch_count - d0
    assert got == -(-len(ticks) // T), (got, len(ticks))
    assert f.mapper.dispatch_count == 0
    _assert_maps_equal(_map_snap(h), _map_snap(f))


def test_mid_backlog_format_switch():
    """Stream 0 switches scan modes mid-backlog: the decode reset (and
    ring invalidation) land at its own tick inside the super-step, the
    carried map SURVIVES the switch (host-route semantics), and both
    routes agree byte-for-byte."""
    streams = 2
    dense = _dense_frames(2)
    hq_rev = []
    idx = 0
    ppr = 384  # 4 HQ capsules (96 nodes each) per revolution
    while idx < 2 * ppr:
        pts = (np.arange(96) + idx) % ppr
        dists = 2000.0 + 500.0 * np.sin(2 * np.pi * pts / ppr)
        angle_q14 = (pts * 65536) // ppr
        flags = np.where(pts == 0, 1, 0)
        hq_rev.append(wire.encode_hq_capsule(
            angle_q14, (dists * 4).astype(np.int64),
            np.full(96, 190), flags,
        ))
        idx += 96
    ticks = _byte_ticks(dense, streams)
    hq_ticks = _byte_ticks(
        hq_rev, streams, t0=200.0, ans=int(Ans.MEASUREMENT_HQ)
    )
    # stream 1 stays dense-idle during the switch ticks
    for t in hq_ticks:
        t[1] = None
    scene = ticks + hq_ticks

    h = _build("host", streams)
    f = _build("fused", streams, super_tick_max=4)
    for t in scene:
        h.submit_bytes(t)
    f.submit_bytes_backlog(scene)
    _assert_maps_equal(_map_snap(h), _map_snap(f))
    # the map absorbed updates on both sides of the switch
    assert int(np.asarray(_map_snap(f)["revision"])[0]) > 0


def test_all_idle_tick_does_not_republish_stale_poses():
    """PR 10's ``last_poses``-clearing fix, extended to the in-program
    mapping path: a tick that pushes no sub-sweep anywhere must land
    ``last_poses = [None] * streams`` even though the previous tick
    published real estimates."""
    streams = 2
    f = _build("fused", streams)
    ticks = _byte_ticks(_dense_frames(2), streams)
    for t in ticks:
        f.submit_bytes(t)
    assert any(p is not None for p in f.last_poses)
    idle = [None] * streams
    f.submit_bytes(idle)
    assert f.last_poses == [None] * streams


# ---------------------------------------------------------------------------
# snapshot / failover transport from the new carry layout
# ---------------------------------------------------------------------------


def test_snapshot_restore_mid_super_tick():
    """Mid-run per-stream snapshot (ingest v3 — map rows inside the
    carry) restored into a FRESH service resumes bit-exactly: the
    migration restore (restore_decode=True) moves decode, filter AND
    map rows in one transport unit."""
    streams = 2
    ticks = _byte_ticks(_dense_frames(4), streams)
    cut = len(ticks) // 2

    ref = _build("fused", streams, super_tick_max=2)
    for t in ticks[:cut]:
        ref.submit_bytes(t)
    snaps = [
        ref.fleet_ingest.snapshot_stream(i) for i in range(streams)
    ]
    assert any(k.startswith("ingest.map_") for k in snaps[0])

    dst = _build("fused", streams, super_tick_max=2)
    for i, snap in enumerate(snaps):
        assert dst.fleet_ingest.restore_stream(i, snap, restore_decode=True)
    for t in ticks[cut:]:
        ref.submit_bytes(t)
        dst.submit_bytes(t)
        assert _pose_row(ref) == _pose_row(dst)
    _assert_maps_equal(_map_snap(ref), _map_snap(dst))


def test_snapshot_version_skew_rejected():
    """A v2-stamped (pre-carry-layout) snapshot is rejected with the
    state untouched, and a mapping-off snapshot cannot restore_decode
    into a mapping-on engine (ingest key-space mismatch)."""
    streams = 2
    svc = _build("fused", streams)
    for t in _byte_ticks(_dense_frames(1), streams):
        svc.submit_bytes(t)
    snap = svc.fleet_ingest.snapshot_stream(0)
    bad = dict(snap)
    bad["version"] = np.asarray(2, np.int32)
    assert not svc.fleet_ingest.restore_stream(0, bad)
    assert not svc.fleet_ingest.restore_stream(0, bad, restore_decode=True)
    # mapping-off key space (map rows stripped) into a mapping-on
    # engine: the exact-key check refuses the migration restore
    stripped = {
        k: v for k, v in snap.items() if not k.startswith("ingest.map_")
    }
    assert not svc.fleet_ingest.restore_stream(0, stripped, restore_decode=True)
    # the plain rejoin restore ignores ingest rows and still works
    assert svc.fleet_ingest.restore_stream(0, stripped)


def test_carried_map_checkpoint_interops_with_fleetmapper():
    """The carried view's per-stream map snapshot is FleetMapper's
    format byte-for-byte: a row pulled from the carry restores into a
    host-backend FleetMapper and back."""
    from rplidar_ros2_driver_tpu.mapping.mapper import FleetMapper

    streams = 2
    f = _build("fused", streams)
    for t in _byte_ticks(_dense_frames(2), streams):
        f.submit_bytes(t)
    row = f.mapper.snapshot_stream(0)
    assert int(np.asarray(row["revision"])) > 0

    host = FleetMapper(_params("host"), streams, beams=BEAMS)
    assert host.restore_stream(1, row)
    back = host.snapshot_stream(1)
    for k in ("log_odds", "pose", "origin_xy", "revision"):
        np.testing.assert_array_equal(
            np.asarray(row[k]), np.asarray(back[k])
        )
    # and back into the carry
    assert f.mapper.restore_stream(1, back)
    row1 = f.mapper.snapshot_stream(1)
    for k in ("log_odds", "pose", "origin_xy", "revision"):
        np.testing.assert_array_equal(
            np.asarray(row[k]), np.asarray(row1[k])
        )
    # version skew rejected by the carried view too
    bad = dict(row)
    bad["version"] = np.asarray(99, np.int32)
    assert not f.mapper.restore_stream(0, bad)


# ---------------------------------------------------------------------------
# loop-closure tap + seam validation
# ---------------------------------------------------------------------------


def test_failover_transport_carried_map():
    """The elastic pod on the fused route: a chaos shard kill's victims
    restore onto survivors WITH their in-carry map rows — the map
    travels inside the v3 ingest snapshot (no duplicate mapper-side
    pull; the snapshot store's entries carry no separate "map" key),
    and the evacuated stream's map revision survives the migration."""
    from rplidar_ros2_driver_tpu.driver.chaos import (
        ShardChaosConfig,
        ShardChaosSchedule,
    )
    from rplidar_ros2_driver_tpu.parallel.service import ElasticFleetService

    streams, shards = 2, 2
    params = _params(
        "fused",
        shard_count=shards, shard_lanes=2,
        failover_snapshot_ticks=2,
        shard_backoff_base_s=0.45, shard_backoff_max_s=2.0,
        shard_backoff_jitter=0.0, shard_probation_ticks=2,
    )
    fake = {"now": 0.0}
    pod = ElasticFleetService(
        params, streams, shards=shards, beams=BEAMS, capacity=1024,
        fleet_ingest_buckets=(4,), clock=lambda: fake["now"],
    )
    pod.attach_shard_chaos(ShardChaosSchedule(ShardChaosConfig(
        kills=((1, 8, 10),),
    )))
    pod.precompile([DENSE])
    assert pod.shards[0].mapper.backend == "carried"
    ticks = _byte_ticks(_dense_frames(4), streams)
    for tick in ticks:
        pod.submit_bytes(tick)
        fake["now"] += 0.1
    kinds = [e[1] for e in pod.events]
    assert "lost" in kinds and "evacuated" in kinds
    # the snapshot store never carried a duplicate mapper-side row
    for _t, snap in pod._snap.values():
        assert "map" not in snap
        assert any(k.startswith("ingest.map_") for k in snap["ingest"])
    # the evacuated stream kept a live map on its new lane: revision
    # positive and still advancing post-migration
    victim = pod.events[[i for i, e in enumerate(pod.events)
                         if e[1] == "evacuated"][0]][2]
    got = pod.topology.placement(victim)
    assert got is not None
    s, lane = got
    row = pod.shards[s].mapper.snapshot_stream(lane)
    assert int(np.asarray(row["revision"])) > 0


def test_loop_closure_tap_parity():
    """The loop engine observes the fused route exactly as it observes
    the host route: same submap finalizations, same check cadence, same
    corrected poses (the carried mapper feeds it the identical scan
    windows and estimates)."""
    streams = 2
    over = dict(
        loop_enable=True, loop_backend="host",
        loop_submap_revs=2, loop_check_revs=2, loop_max_submaps=4,
        loop_candidates=1, loop_min_points=4, pose_graph_iters=16,
    )
    h = _build("host", streams, **over)
    f = _build("fused", streams, **over)
    h.attach_loop_closure()
    f.attach_loop_closure()
    for t in _byte_ticks(_dense_frames(4), streams):
        h.submit_bytes(t)
        f.submit_bytes(t)
        assert [
            None if c is None else tuple(int(v) for v in c)
            for c in h.last_corrected_poses
        ] == [
            None if c is None else tuple(int(v) for v in c)
            for c in f.last_corrected_poses
        ]
    assert f.loop.installs == h.loop.installs
    assert f.loop.installs > 0
    assert f.loop.checks == h.loop.checks


def test_seam_validation():
    """Config + attach validation: the fused route refuses to build
    half-wired."""
    from rplidar_ros2_driver_tpu.mapping.mapper import FleetMapper

    with pytest.raises(ValueError, match="requires map_enable"):
        _params("fused", map_enable=False).validate()
    with pytest.raises(ValueError, match="requires deskew_enable"):
        _params("fused", deskew_enable=False).validate()
    # the fleet seam must be SPELLED fused: the single-stream fused
    # seam satisfies the deskew check but never builds cfg.mapping
    with pytest.raises(ValueError, match="fleet_ingest_backend"):
        _params(
            "fused", fleet_ingest_backend="auto", ingest_backend="fused"
        ).validate()
    _params("fused").validate()
    # an explicit dispatching FleetMapper beside the carry is refused
    svc = _build("fused", 2)
    with pytest.raises(ValueError, match="fused_mapping_backend"):
        svc.attach_mapper(FleetMapper(_params("host"), 2, beams=BEAMS))
    # and the carried view has no submit path
    with pytest.raises(RuntimeError, match="absorb_wires"):
        svc.mapper.submit([None, None])
