"""The examples/ workflows must keep running end-to-end (CPU)."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize(
    "script,extra",
    [
        ("single_lidar.py", ["--seconds", "3"]),
        ("fleet_gateway.py", ["--ticks", "3"]),
        ("record_replay.py", ["--seconds", "2"]),
        ("multihost_fleet.py", ["--ticks", "2"]),
    ],
)
def test_example_runs(script, extra):
    # the examples wait on outcomes (first scan / min revolutions) with
    # generous internal deadlines instead of racing fixed clocks, so the
    # harness budget only needs to exceed their worst-case give-up sum
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", script), "--cpu", *extra],
        capture_output=True,
        text=True,
        timeout=360,
        cwd=_ROOT,
    )
    if out.returncode != 0 and (
        "Multiprocess computations aren't implemented on the CPU backend"
        in out.stdout + out.stderr
    ):
        # capability probe, same contract as test_multiprocess: this
        # jaxlib's CPU backend has no cross-process collective runtime,
        # so the multihost example CANNOT run here — only this exact
        # signature downgrades to a skip; any other failure stays loud
        pytest.skip(
            "CPU backend lacks multiprocess collectives "
            "(\"Multiprocess computations aren't implemented on the "
            "CPU backend\") — the multihost example needs a device "
            "runtime with cross-process support"
        )
    assert out.returncode == 0, (out.stdout, out.stderr)
