"""Fleet gateway: N lidars through ONE sharded device program.

Each simulated device gets its own driver stack (native channel ->
decode -> assembly); every tick stacks the newest revolution per stream
into a single counted upload and runs the `(stream, beam)`-sharded chain
step — one dispatch for the whole fleet.  Finishes with an Orbax
checkpoint of the sharded state (per-process shard writes, no host
gather) and a restore into a fresh service.

    python examples/fleet_gateway.py [--cpu] [--streams 2] [--ticks 5]
"""

import argparse
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--streams", type=int, default=2)
    ap.add_argument("--ticks", type=int, default=5)
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService

    sims = [SimulatedDevice().start() for _ in range(args.streams)]
    drvs = []
    ok = False
    try:
        for sim in sims:
            d = RealLidarDriver(channel_type="tcp", tcp_host="127.0.0.1",
                                tcp_port=sim.port, motor_warmup_s=0.0)
            assert d.connect("sim", 0, False)
            d.detect_and_init_strategy()
            assert d.start_motor("DenseBoost", 600)
            drvs.append(d)

        params = DriverParams(filter_backend="cpu" if args.cpu else "tpu",
                              filter_window=4,
                              filter_chain=("clip", "median", "voxel"),
                              voxel_grid_size=64)
        svc = ShardedFilterService(params, streams=args.streams,
                                   beams=256, capacity=4096)
        captures = [[] for _ in drvs]
        for tick in range(args.ticks):
            scans = []
            for s, d in enumerate(drvs):
                got = d.grab_scan_host(2.0)
                scans.append(got[0] if got else None)
                if got:
                    captures[s].append(got[0])
            # pipelined fleet tick: collect the PREVIOUS tick's outputs
            # while this tick computes (one tick of declared staleness —
            # the publish never waits on device compute)
            outs = svc.submit_pipelined(scans)
            live = sum(o is not None for o in outs)
            occ = [int(np.asarray(o.voxel).sum()) if o else 0 for o in outs]
            print(f"tick {tick}: {live}/{args.streams} streams (prev tick), "
                  f"voxel occ {occ}")
        tail = svc.flush_pipelined()
        if tail is not None:
            live = sum(o is not None for o in tail)
            print(f"drained final tick: {live}/{args.streams} streams")

        # the same revolutions again, offline: fused fleet replay over the
        # service's mesh — one dispatch per chunk for the whole fleet
        if all(len(c) >= 1 for c in captures):
            from rplidar_ros2_driver_tpu.replay import replay_fleet

            ranges, _ = replay_fleet(
                captures, params, mesh=svc.mesh, beams=256,
                capacity=4096, chunk=8,
            )
            print(
                f"fleet replay: {ranges.shape[1]} revs/stream re-filtered "
                f"offline -> ranges {ranges.shape}"
            )

        import tempfile

        ckpt_dir = os.path.join(tempfile.mkdtemp(prefix="fleet_ckpt_"), "ckpt")
        try:
            svc.save_sharded(ckpt_dir)
            svc2 = ShardedFilterService(params, streams=args.streams,
                                        beams=256, capacity=4096)
            ok = svc2.load_sharded(ckpt_dir)
            print(f"orbax restore into a fresh service: {'ok' if ok else 'FAILED'}")
        finally:
            shutil.rmtree(os.path.dirname(ckpt_dir), ignore_errors=True)
    finally:
        for d in drvs:
            try:
                d.stop_motor()
                d.disconnect()
            except Exception:
                pass
        for s in sims:
            try:
                s.stop()
            except Exception:
                pass
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
