"""Record wire frames live, then batch-decode and re-filter offline.

The capture tee sits at the decoder (every measurement frame + arrival
time, before any lossy processing), so a recording replays bit-exactly:
offline decode runs whole frame-runs through the vectorized unpack
kernels, and `replay_through_chain` pushes the recovered revolutions
through the same fused chain the live path uses — `lax.scan`-fused,
hundreds of revolutions per dispatch.

    python examples/record_replay.py [--cpu] [--seconds 3]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--seconds", type=float, default=3.0)
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
    from rplidar_ros2_driver_tpu.replay import decode_recording, replay_through_chain

    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".rpl", delete=False) as f:
        path = f.name
    sim = SimulatedDevice().start()
    try:
        drv = RealLidarDriver(channel_type="tcp", tcp_host="127.0.0.1",
                              tcp_port=sim.port, motor_warmup_s=0.0)
        assert drv.connect("sim", 0, False)
        drv.detect_and_init_strategy()
        assert drv.start_motor("DenseBoost", 600)
        drv.start_recording(path)
        # run for --seconds, but gate on the OUTCOME: at least 3 grabbed
        # revolutions (with a generous ceiling), so a loaded box cannot
        # produce an empty recording and a spurious failure
        t_end = time.monotonic() + args.seconds
        t_giveup = time.monotonic() + max(args.seconds, 60.0)
        grabbed = 0
        while time.monotonic() < t_end or (
            grabbed < 3 and time.monotonic() < t_giveup
        ):
            if drv.grab_scan_host(2.0) is not None:
                grabbed += 1
        frames = drv.stop_recording()
        drv.stop_motor()
        drv.disconnect()
        print(f"live: {grabbed} revolutions grabbed, {frames} frames captured")
    finally:
        sim.stop()

    try:
        rec = decode_recording(path)
        revs = rec.revolutions()
        print(f"offline decode: {rec.num_nodes} nodes in {len(rec.runs)} runs "
              f"-> {len(revs)} complete revolutions")

        params = DriverParams(filter_backend="cpu" if args.cpu else "tpu",
                              filter_window=4,
                              filter_chain=("clip", "median", "voxel"),
                              voxel_grid_size=64)
        ranges, final_state = replay_through_chain(revs, params, beams=256, chunk=64)
        print(f"chain replay: per-rev range images {ranges.shape}, "
              f"final voxel occupancy {int(final_state.voxel_acc.sum())}")
    finally:
        os.unlink(path)
    return 0 if len(revs) > 0 and ranges.shape[0] == len(revs) else 1


if __name__ == "__main__":
    raise SystemExit(main())
