"""Single lidar, full pipeline: lifecycle node + TPU filter chain.

The everyday deployment: one device (here the protocol-accurate
simulator standing in over TCP), the 5-state fault-tolerant FSM, and the
fused filter chain publishing ranges + a rolling voxel occupancy grid.
Also shows the checkpoint surface: the rolling window survives a
deactivate/activate cycle.

    python examples/single_lidar.py [--cpu] [--seconds 5]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu", action="store_true", help="force the CPU JAX backend")
    ap.add_argument("--seconds", type=float, default=5.0)
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
    from rplidar_ros2_driver_tpu.node.node import RPlidarNode

    sim = SimulatedDevice().start()
    params = DriverParams(
        channel_type="tcp",
        scan_mode="DenseBoost",
        filter_backend="cpu" if args.cpu else "tpu",
        filter_chain=("clip", "median", "voxel"),
        filter_window=8,
        voxel_grid_size=128,
    )
    node = RPlidarNode(
        params,
        driver_factory=lambda: RealLidarDriver(
            channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
            motor_warmup_s=0.0,
        ),
    )
    def wait_for(pred, deadline_s: float) -> bool:
        t_end = time.monotonic() + deadline_s
        while time.monotonic() < t_end:
            if pred():
                return True
            time.sleep(0.05)
        return False

    try:
        assert node.configure() and node.activate()
        # wait on the OUTCOME (first published scan), not a fixed clock:
        # chain jit-compile + FSM warmup on a loaded box can outlast any
        # small budget, and a wall-clock race here is a coin flip
        assert wait_for(lambda: node.publisher.scan_count >= 1, 120.0), (
            "no scan published within 120 s"
        )
        t_end = time.monotonic() + args.seconds
        while time.monotonic() < t_end:
            time.sleep(1.0)
            pub = node.publisher
            occ = int(pub.clouds[-1].voxel.sum()) if pub.clouds else 0
            print(f"scans={pub.scan_count} voxel_occupancy={occ} "
                  f"diag={node.diagnostics.last.message}")
        # checkpoint across a lifecycle bounce: the window survives
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
            ckpt = f.name
        try:
            node.save_checkpoint(ckpt)
            before = node.publisher.scan_count
            node.deactivate()
            node.activate()
            restored = node.load_checkpoint(ckpt)
            # same outcome-based wait: the reactivated FSM re-runs
            # connect/warmup, which has no fixed upper bound under load
            wait_for(lambda: node.publisher.scan_count > before, 60.0)
            after = node.publisher.scan_count
            print(f"resumed: restore={restored} scans {before} -> {after}")
            ok = restored and after > before
        finally:
            import os

            os.unlink(ckpt)
    finally:
        node.shutdown()
        sim.stop()
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
