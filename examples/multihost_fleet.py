"""Multi-controller fleet: two jax.distributed processes, one mesh.

Self-launches two worker processes (the parent is only a launcher), each
owning one lidar stream.  The workers join via
``parallel.multihost.initialize`` (standard coordinator env vars), build
the global stream-major ``(stream, beam)`` mesh, and tick
``ShardedFilterService.submit_local`` — each process uploads ONLY its
own stream's revolutions (`jax.make_array_from_process_local_data`, so
ingest never crosses hosts) and reads back only its own output shards.
On a real pod the same code spans hosts; here the two processes share
one machine with 2 virtual CPU devices each (gloo collectives standing
in for ICI/DCN).

    python examples/multihost_fleet.py [--ticks 5]
"""

import argparse
import os
import socket
import subprocess
import sys
import textwrap

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    port, pid, ticks = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(pid)

    sys.path.insert(0, os.getcwd())  # launcher sets cwd to the repo root
    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.driver.dummy import DummyLidarDriver
    from rplidar_ros2_driver_tpu.parallel import multihost
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService

    assert multihost.initialize()
    mesh = multihost.make_global_mesh(stream=2)  # rows align to processes
    print(f"proc {pid}: joined, mesh {dict(mesh.shape)} over "
          f"{jax.process_count()} processes", flush=True)

    params = DriverParams(filter_backend="cpu", filter_window=4,
                          filter_chain=("clip", "median", "voxel"),
                          voxel_grid_size=32)
    svc = ShardedFilterService(params, streams=2, mesh=mesh, beams=256,
                               capacity=1024)
    lidar = DummyLidarDriver()         # this host's OWN sensor
    lidar.connect("dummy", 0, False)
    lidar.start_motor("", 600)
    for tick in range(ticks):
        scan, _ts0, _dur = lidar.grab_scan_host(2.0)
        outs = svc.submit_local([scan])   # collective: both procs tick
        occ = int(outs[0].voxel.sum())
        print(f"proc {pid} tick {tick}: voxel occ {occ}", flush=True)

    # pipelined ticks: publish tick N-1 while N computes — the collect
    # touches only this process's shards, so the collective cadence stays
    # identical across peers (ALL processes must use the pipelined
    # variant together; see submit_local_pipelined's docstring)
    for tick in range(ticks):
        scan, _ts0, _dur = lidar.grab_scan_host(2.0)
        prev = svc.submit_local_pipelined([scan])
        label = (
            f"{int(prev[0].voxel.sum())}" if prev[0] is not None else "(warming)"
        )
        print(f"proc {pid} pipelined tick {tick}: prev-tick occ {label}",
              flush=True)
    tail = svc.flush_pipelined()
    if tail is not None and tail[0] is not None:
        print(f"proc {pid}: drained final tick occ {int(tail[0].voxel.sum())}",
              flush=True)
    lidar.stop_motor()
    lidar.disconnect()
    print(f"proc {pid}: done", flush=True)
    """
)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ticks", type=int, default=3)
    # accepted for symmetry with the other examples; the workers force
    # the CPU backend themselves (virtual 2-device processes)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def launch_once(port: int):
        here = os.path.dirname(os.path.abspath(__file__))
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _WORKER, str(port), str(i), str(args.ticks)],
                cwd=os.path.dirname(here),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            for i in range(2)
        ]
        # timeout well under any harness timeout, and a hung worker takes
        # its sibling down with it (a lone survivor would orphan holding
        # the coordinator port)
        outs = ["", ""]
        try:
            for i, p in enumerate(procs):
                try:
                    outs[i], _ = p.communicate(timeout=120)
                except subprocess.TimeoutExpired:
                    for q in procs:
                        if q.poll() is None:
                            q.kill()
                    outs[i], _ = p.communicate()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        return procs, outs

    # the free-port probe races other processes binding it (TOCTOU):
    # one retry with a fresh port covers the window — but only when the
    # failure looks like a bind/coordinator problem, so genuine worker
    # failures stay fast and keep their first-attempt diagnostics
    port_errors = ("Address already in use", "Failed to bind", "UNAVAILABLE",
                   "coordination service")
    for attempt in range(2):
        procs, outs = launch_once(free_port())
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 0 and not any(
            e in out for e in port_errors for out in outs
        ):
            break
    ok = True
    for i, p in enumerate(procs):
        print(f"--- worker {i} (rc={p.returncode}) ---")
        print(outs[i].strip())
        ok = ok and p.returncode == 0 and "done" in outs[i]
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
