"""Multi-controller fleet: N jax.distributed processes, one mesh.

Two modes, one worker code path:

* **Demo (default)**: self-launches two worker processes on this machine
  (the parent is only a launcher), each owning one lidar stream, with 2
  virtual CPU devices per process (gloo collectives standing in for
  ICI/DCN).

* **Pod runbook (--worker)**: the one command each host of a real pod
  runs.  Set the standard coordinator variables and start the same
  command on every host — the worker joins via
  ``parallel.multihost.initialize``, builds the global stream-major
  ``(stream, beam)`` mesh, and ticks the pipelined fleet:

      JAX_COORDINATOR_ADDRESS=host0:8476 \\
      JAX_NUM_PROCESSES=4 JAX_PROCESS_ID=<this host's id> \\
      python examples/multihost_fleet.py --worker --ticks 100

  Each process uploads ONLY its own streams' revolutions
  (``jax.make_array_from_process_local_data`` — ingest never crosses
  hosts) and reads back only its own output shards; XLA routes the
  beam-axis psum over ICI within a host and DCN across hosts.  Swap the
  DummyLidarDriver for ``RealLidarDriver(port=...)`` per stream to feed
  real sensors (docs/MULTIHOST_RUNBOOK.md).

    python examples/multihost_fleet.py [--ticks 5]
"""

import argparse
import os
import socket
import subprocess
import sys

# the runbook invokes this file directly from any cwd: python only adds
# examples/ to sys.path, so the package root must be added explicitly
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_worker(ticks: int, streams_per_host: int = 1,
               window: int = 4, demo_cpu: bool = False,
               allow_single: bool = False) -> int:
    """The per-process fleet worker — the pod runbook entry point.

    Topology comes from the standard coordinator env variables (see
    module docstring).  ``demo_cpu`` is the local-demo switch: force the
    CPU backend via jax.config (the env var alone can be overridden by
    site shims that pre-set the platform config at interpreter start).
    """
    if demo_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.driver.dummy import DummyLidarDriver
    from rplidar_ros2_driver_tpu.parallel import multihost
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService

    if not multihost.initialize():
        # a pod worker with no coordinator must FAIL here, not degrade:
        # this host would tick its own 1-process mesh and exit 0 looking
        # healthy while every peer blocks in initialize() waiting for it
        if not allow_single:
            print("error: no multi-process topology configured "
                  f"({multihost._COORD_ENV} unset); a pod worker "
                  "must not silently run alone — pass --single-process "
                  "for a deliberate 1-process smoke run",
                  file=sys.stderr, flush=True)
            return 2
        print("single-process smoke run (--single-process)", flush=True)
    pid, nproc = jax.process_index(), jax.process_count()
    streams = nproc * streams_per_host
    mesh = multihost.make_global_mesh(stream=streams)
    print(f"proc {pid}: joined, mesh {dict(mesh.shape)} over "
          f"{nproc} processes", flush=True)

    params = DriverParams(filter_window=window,
                          filter_chain=("clip", "median", "voxel"),
                          voxel_grid_size=32,
                          **({"filter_backend": "cpu"} if demo_cpu else {}))
    svc = ShardedFilterService(params, streams=streams, mesh=mesh,
                               beams=256, capacity=1024)
    # this host's OWN sensors — on a real rig, construct one
    # RealLidarDriver(port=...) per local stream here instead
    lidars = []
    for _ in range(streams_per_host):
        lidar = DummyLidarDriver()
        lidar.connect("dummy", 0, False)
        lidar.start_motor("", 600)
        lidars.append(lidar)

    def grab_local():
        # a grab timeout degrades to an idle row (None) — raising here
        # would abort this process AHEAD of the collective while every
        # peer blocks inside theirs (submit_local's docstring)
        grabs = [lidar.grab_scan_host(2.0) for lidar in lidars]
        return [g[0] if g is not None else None for g in grabs]

    for tick in range(ticks):
        outs = svc.submit_local(grab_local())  # collective: all procs tick
        label = (
            f"voxel occ {int(outs[0].voxel.sum())}"
            if outs[0] is not None else "(idle)"
        )
        print(f"proc {pid} tick {tick}: {label}", flush=True)

    # pipelined ticks: publish tick N-1 while N computes — the collect
    # touches only this process's shards, so the collective cadence stays
    # identical across peers (ALL processes must use the pipelined
    # variant together; see submit_local_pipelined's docstring)
    for tick in range(ticks):
        prev = svc.submit_local_pipelined(grab_local())
        label = (
            f"{int(prev[0].voxel.sum())}" if prev[0] is not None else "(warming)"
        )
        print(f"proc {pid} pipelined tick {tick}: prev-tick occ {label}",
              flush=True)
    tail = svc.flush_pipelined()
    if tail is not None and tail[0] is not None:
        print(f"proc {pid}: drained final tick occ {int(tail[0].voxel.sum())}",
              flush=True)
    for lidar in lidars:
        lidar.stop_motor()
        lidar.disconnect()
    print(f"proc {pid}: done", flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ticks", type=int, default=3)
    ap.add_argument("--worker", action="store_true",
                    help="run as ONE fleet process (the pod runbook "
                    "command — topology from JAX_COORDINATOR_ADDRESS / "
                    "JAX_NUM_PROCESSES / JAX_PROCESS_ID)")
    ap.add_argument("--streams-per-host", type=int, default=1)
    ap.add_argument("--window", type=int, default=4,
                    help="rolling temporal-median window per stream")
    ap.add_argument("--single-process", action="store_true",
                    help="with --worker: deliberately run a 1-process "
                    "fleet without a coordinator (smoke runs only — a "
                    "pod worker missing its coordinator is otherwise a "
                    "hard error)")
    ap.add_argument("--demo-cpu", action="store_true",
                    help=argparse.SUPPRESS)  # set by the demo launcher
    # accepted for symmetry with the other examples; the demo workers
    # force the CPU backend themselves (virtual 2-device processes)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.worker:
        # --cpu forces the CPU backend in worker mode too (the hidden
        # --demo-cpu is how the demo launcher asks for the same thing)
        return run_worker(args.ticks, args.streams_per_host,
                          window=args.window,
                          demo_cpu=args.demo_cpu or args.cpu,
                          allow_single=args.single_process)

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def launch_once(port: int):
        here = os.path.dirname(os.path.abspath(__file__))
        repo = os.path.dirname(here)
        procs = []
        for i in range(2):
            env = dict(
                os.environ,
                XLA_FLAGS="--xla_force_host_platform_device_count=2",
                JAX_PLATFORMS="cpu",
                JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                JAX_NUM_PROCESSES="2",
                JAX_PROCESS_ID=str(i),
                PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
            )
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--worker",
                 "--demo-cpu", "--ticks", str(args.ticks),
                 "--streams-per-host", str(args.streams_per_host),
                 "--window", str(args.window)],
                cwd=repo, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            ))
        # timeout well under any harness timeout, and a hung worker takes
        # its sibling down with it (a lone survivor would orphan holding
        # the coordinator port)
        outs = ["", ""]
        try:
            for i, p in enumerate(procs):
                try:
                    outs[i], _ = p.communicate(timeout=120)
                except subprocess.TimeoutExpired:
                    for q in procs:
                        if q.poll() is None:
                            q.kill()
                    outs[i], _ = p.communicate()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        return procs, outs

    # the free-port probe races other processes binding it (TOCTOU):
    # one retry with a fresh port covers the window — but only when the
    # failure looks like a bind/coordinator problem, so genuine worker
    # failures stay fast and keep their first-attempt diagnostics
    port_errors = ("Address already in use", "Failed to bind", "UNAVAILABLE",
                   "coordination service")
    for attempt in range(2):
        procs, outs = launch_once(free_port())
        if all(p.returncode == 0 for p in procs):
            break
        if attempt == 0 and not any(
            e in out for e in port_errors for out in outs
        ):
            break
    ok = True
    for i, p in enumerate(procs):
        print(f"--- worker {i} (rc={p.returncode}) ---")
        print(outs[i].strip())
        ok = ok and p.returncode == 0 and "done" in outs[i]
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
