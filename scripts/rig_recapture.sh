#!/bin/sh
# One rig window -> every queued on-chip measurement, in sequence
# (the remote link serves ONE client at a time — never parallelize):
#   1. config 5 headline (device-resident in-jit + median A/B + sidecar)
#   2. config 6 e2e (post-reorder pipelined publish tail distributions)
#   3. deep-window median A/B at W=256/512 (3000-iter discipline)
#   4. streaming-step ablation (decides resample_backend's TPU mapping)
# Each line of the output artifact is one command's JSON (or a failure
# record); stderr goes to the sidecar .log.  Probe budgets are
# env-tunable (BENCH_PROBE_BUDGET_S et al.).
set -u
cd "$(dirname "$0")/.."
out="artifacts/rig_recapture_$(date +%Y%m%d_%H%M).jsonl"
mkdir -p artifacts
for cmd in \
    "python bench.py --config 5" \
    "python bench.py --config 6" \
    "python scripts/deep_window_ab.py --windows 256 512" \
    "python scripts/step_ablation.py"; do
  echo "{\"cmd\": \"$cmd\"}" >> "$out"
  tmp=$(mktemp)
  $cmd > "$tmp" 2>> "$out.log"
  if [ -s "$tmp" ]; then
    # the command spoke for itself (a measurement, a device_unavailable
    # fallback, or an {"error": ...} line) — exactly one record each
    cat "$tmp" >> "$out"
  else
    echo "{\"failed\": \"$cmd\"}" >> "$out"
  fi
  rm -f "$tmp"
done
echo "$out"
