#!/bin/sh
# One rig window -> every queued on-chip measurement, in sequence
# (the remote link serves ONE client at a time — never parallelize,
# and do not share this box with CPU-heavy jobs while measuring: a
# starved relay wedges the tunnel).
#
# Default queue (r5 — VERDICT r4 items 1-4, 7, 9 in priority order):
#   1. config 5 headline (RTT-adaptive in-jit rounds + 4-arm median A/B
#      incl. the pinned inc_xla/inc_pallas lowering A/B)
#   2. config 6 e2e (pipelined publish tail, collect-wait + upload/
#      dispatch decomposed — the clean-link post-reorder p99)
#   3. deep-window median A/B at W=256/512 (--iters auto, pinned arms)
#   4. streaming-step ablation (--iters auto: unbiased absolutes,
#      post-fold clip confirmation, voxel matmul arm)
#   5. live multi-stream pipelined fleet latency artifact
#   6. fleet ingest A/B (config 10: host-decode-then-batch vs fleet-fused
#      per tick — the fleet_ingest_backend decision key)
#   7. live fleet latency, fleet-fused arm (same publish-tick pairing)
#   8. super-tick drain A/B (config 11: T fleet ticks per compiled
#      dispatch vs one each — the super_tick_max decision key; on-chip
#      every amortized dispatch is a link round trip)
#   9. SLAM front-end A/B (config 12: N-stream correlative match +
#      log-odds update, host reference vs one vmapped dispatch per
#      fleet tick — the map_backend decision key)
#  10. degraded-fleet chaos throughput (config 13: N=8 streams, K of
#      them quarantined by the health FSM under a seeded fault
#      program — healthy-lane throughput vs the K=0 baseline, zero
#      recompiles across quarantine/rejoin asserted)
#  11. correlative-matcher kernel A/B (config 14: xla vs the VMEM-tiled
#      pallas score-volume + log-odds-update kernels, bit-exact parity
#      + zero recompiles asserted — the FIRST Mosaic compile of these
#      kernels happens here; the match_backend decision key
#      `pallas_match_ab` only counts on-chip, non-interpret records)
# Override by passing commands as arguments (one quoted string each).
#
# WAIT_FOR_LINK_S=<seconds>: probe the backend in a throwaway child
# every 5 min for up to that long before starting (for catching the
# next window of a currently-wedged tunnel).
#
# Each line of the output artifact is one command's JSON (or a failure
# record); stderr goes to the sidecar .log.  Probe budgets are
# env-tunable (BENCH_PROBE_BUDGET_S et al.).
set -u
cd "$(dirname "$0")/.."
out="artifacts/rig_recapture_$(date +%Y%m%d_%H%M).jsonl"
mkdir -p artifacts

# fail fast on a dirty tree: a rig window burned measuring code that
# violates the repo invariants (trace-safety, donation, bit-exactness —
# tools/graftlint) is not publishable evidence.  Cheap (AST-only, no
# device), so it runs before any link probing.
JAX_PLATFORMS=cpu python -m rplidar_ros2_driver_tpu.tools.graftlint --jobs auto >> "$out.log" 2>&1
lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
  echo '{"error": "graftlint found unbaselined findings - fix the tree before burning a rig window (see the sidecar log)", "graftlint_exit": '"$lint_rc"'}' >> "$out"
  echo "$out"
  exit 4
fi

case "${WAIT_FOR_LINK_S:-0}" in
  *[!0-9]*)
    echo "WAIT_FOR_LINK_S must be a whole number of seconds, got: ${WAIT_FOR_LINK_S}" >&2
    exit 2 ;;
esac
if [ "${WAIT_FOR_LINK_S:-0}" -gt 0 ]; then
  deadline=$(( $(date +%s) + WAIT_FOR_LINK_S ))
  while :; do
    if timeout 120 python -c "import jax; jax.devices()" 2>> "$out.log"; then
      echo "link up at $(date -u)" >> "$out.log"
      break
    fi
    now=$(date +%s)
    if [ "$now" -ge "$deadline" ]; then
      echo "{\"error\": \"link still down after ${WAIT_FOR_LINK_S}s of waiting\"}" >> "$out"
      echo "$out"
      exit 3
    fi
    echo "link down at $(date -u); retrying in 300 s" >> "$out.log"
    sleep 300
  done
fi

if [ $# -eq 0 ]; then
  set -- \
    "python bench.py --config 5" \
    "python bench.py --config 6" \
    "python scripts/deep_window_ab.py --windows 256 512" \
    "python scripts/step_ablation.py" \
    "python scripts/fleet_latency.py" \
    "python bench.py --config 10" \
    "python scripts/fleet_latency.py --fleet-ingest fused" \
    "python bench.py --config 11" \
    "python bench.py --config 12" \
    "python bench.py --config 13" \
    "python bench.py --config 14" \
    "python bench.py --config 15" \
    "python bench.py --config 16" \
    "python bench.py --config 17" \
    "python bench.py --config 18" \
    "python bench.py --config 19" \
    "python bench.py --config 20" \
    "python bench.py --config 21" \
    "python bench.py --config 22" \
    "python bench.py --config 23"
fi
for cmd in "$@"; do
  # NOTE: commands are split on whitespace (plain sh expansion) — pass
  # simple space-separated words only, no shell quoting inside a command
  cmd_json=$(printf '%s' "$cmd" | sed 's/\\/\\\\/g; s/"/\\"/g')
  echo "{\"cmd\": \"$cmd_json\"}" >> "$out"
  tmp=$(mktemp)
  $cmd > "$tmp" 2>> "$out.log"
  if [ -s "$tmp" ]; then
    # the command spoke for itself (a measurement, a device_unavailable
    # fallback, or an {"error": ...} line) — exactly one record each
    cat "$tmp" >> "$out"
  else
    echo "{\"failed\": \"$cmd\"}" >> "$out"
  fi
  rm -f "$tmp"
done
echo "$out"
