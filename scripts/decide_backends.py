"""Turn a rig-recapture artifact into `auto`-mapping recommendations.

The standing decision procedure (docs/BENCHMARKS.md) as code: every
`auto` backend default resolves from committed on-chip measurement
artifacts, one bar for all of them.  This tool reads a
`scripts/rig_recapture.sh` JSONL artifact (or any file of one-JSON-
object-per-line measurement records), extracts the decision keys, and
prints the current-vs-recommended table for each mapping — so a link
window converts into resolver flips by reading ONE report instead of
grepping artifacts.

    python scripts/decide_backends.py artifacts/rig_recapture_X.jsonl ...

Only TPU-device records carry decision weight (CPU fallbacks and smoke
runs are reported but never recommend a TPU flip).  Prints a human
table to stderr and ONE machine-readable JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import math
import sys

# the noise bar: a flip needs >5% on the decision key (the config-5
# round spread on a healthy rig is ~1.4%; 5% clears weather without
# hiding a real win)
MARGIN = 1.05


def _records(paths: list[str]):
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    yield rec


_DECISION_KEYS = (
    "median_ab", "deep_window_ab", "derived", "fleet_ingest_ab",
    "super_tick_ab", "mapping_ab", "pallas_match_ab", "failover_ab",
    "deskew_ab", "loop_close_ab", "fused_mapping_ab",
    "elastic_serving_ab", "async_serving_ab", "pod_scaleout_ab",
    "map_serving_ab", "scenario_matrix",
)

# config 23: which scenario-matrix cell flag corroborates which
# mapping flip.  Speed ratios answer "is the backend faster"; the
# scenario matrix answers "does the subsystem still land the accuracy
# claim outside the synthetic ring".  A flip on any of these mappings
# must be corroborated by >= 2 unclamped matrix cells whose flag holds
# — one cell is one layout, and the loop-scene calibration history
# shows single layouts lie (perceptual aliasing, layout-sensitive
# slips).  Clamped cells (wall time under the timer floor) carry no
# corroboration weight, same as every clamped ratio above.
_SCENARIO_CORROBORATION = {
    "deskew_enable.tpu": "deskew_ok",
    "loop_enable.tpu": "loop_ok",
    "loop_backend.tpu": "loop_ok",
    "match_backend.tpu": "match_ok",
}


def _strength(value: float) -> float:
    """Evidence strength of a speedup ratio: |log ratio|, symmetric in
    wins and losses (abs(v-1) would rank a 1.25x win above a 1.30x
    slowdown).  Non-positive ratios are malformed: strength 0 so they
    can never displace real evidence."""
    return abs(math.log(value)) if value > 0 else 0.0


def analyze(records: list[dict]) -> dict:
    """Decision keys -> recommendations.  Pure (testable on synthetic
    records); the CLI wraps it.  Multi-record merges keep the STRONGEST
    evidence per mapping (largest |log ratio|) — last-wins would let a
    degraded-link record mask a healthy one."""
    out: dict = {"recommendations": {}, "evidence": {}, "non_tpu_ignored": []}
    scenario_cells: list[dict] = []

    def recommend(mapping: str, entry: dict) -> None:
        prev = out["recommendations"].get(mapping)
        if prev is None or _strength(entry["value"]) > _strength(prev["value"]):
            out["recommendations"][mapping] = entry

    def ratio_entry(current: str, proposed: str, key: str,
                    value: float, source: str) -> dict:
        return {
            "current": current,
            "recommended": proposed if value > MARGIN else current,
            "flip": value > MARGIN,
            "key": key,
            "value": value,
            "margin": MARGIN,
            "source": source,
        }

    for rec in records:
        if not any(k in rec for k in _DECISION_KEYS):
            continue
        dev = rec.get("device")
        if dev != "tpu":
            # reported once per record, never used for a TPU flip —
            # including device-less records (malformed, but visible)
            out["non_tpu_ignored"].append(
                f"{rec.get('metric') or next(iter(rec), '?')}: device={dev!r}"
            )
            continue

        # config 5 headline: the always-on median A/B
        ab = rec.get("median_ab")
        if isinstance(ab, dict):
            v = ab.get("inc_pallas_vs_headline_speedup")
            if isinstance(v, (int, float)):
                recommend("median_backend.tpu", ratio_entry(
                    "pallas", "inc",
                    "config5 inc_pallas_vs_headline_speedup",
                    float(v), "median_ab",
                ))
            out["evidence"].setdefault("config5_median_ab", []).append({
                k: ab[k] for k in (
                    "speedup", "inc_vs_headline_speedup",
                    "inc_pallas_vs_headline_speedup",
                    "inc_pallas_vs_inc_xla_speedup", "barrier_rtt_ms",
                ) if k in ab
            })

        # deep-window A/B: the window-aware crossover
        dw = rec.get("deep_window_ab")
        if isinstance(dw, dict):
            crossings = {}
            for w, row in sorted(dw.items(), key=lambda kv: int(kv[0])):
                if isinstance(row, dict):
                    v = row.get("inc_vs_best_sort_speedup")
                    if isinstance(v, (int, float)):
                        crossings[int(w)] = float(v)
            out["evidence"].setdefault(
                "deep_window_inc_vs_best_sort", []
            ).append({str(w): v for w, v in crossings.items()})
            # the threshold must be UPWARD-CLOSED: every window at or
            # above it clears the bar (one just-over-margin shallow
            # window must not flip the whole depth range)
            thr = None
            for w in sorted(crossings, reverse=True):
                if crossings[w] > MARGIN:
                    thr = w
                else:
                    break
            if thr is not None:
                recommend("median_backend.tpu.window_threshold", {
                    "current": "pallas at every depth",
                    "recommended": f"inc for window >= {thr} (pallas below)",
                    "flip": True,
                    "key": "deep_window inc_vs_best_sort_speedup",
                    "value": crossings[thr],
                    "margin": MARGIN,
                    "source": "deep_window_ab",
                })
            elif crossings:
                # no depth clears the bar: emit an explicit KEEP entry,
                # so the strongest-evidence merge is symmetric — a
                # healthier artifact showing no crossover can displace a
                # degraded-link record's flip recommendation instead of
                # leaving it unopposed (ADVICE r5 #2).  The entry's
                # strength must come from evidence AGAINST the flip
                # (ratios <= 1: inc losing); a sub-margin ratio > 1 still
                # argues FOR inc, and using its magnitude would let a
                # near-flip record decisively suppress a genuine flip.
                # With no pro-keep ratio at all, carry the weakest ratio
                # (closest to 1) — a deliberately feeble keep.
                pro_keep = [v for v in crossings.values() if v <= 1.0]
                best = (
                    max(pro_keep, key=_strength)
                    if pro_keep
                    else min(crossings.values(), key=_strength)
                )
                recommend("median_backend.tpu.window_threshold", {
                    "current": "pallas at every depth",
                    "recommended": "pallas at every depth",
                    "flip": False,
                    "key": "deep_window inc_vs_best_sort_speedup",
                    "value": best,
                    "margin": MARGIN,
                    "source": "deep_window_ab",
                })

        # config 10: the fleet ingest A/B (fleet_ingest_backend mapping)
        fab = rec.get("fleet_ingest_ab")
        if isinstance(fab, dict):
            v = fab.get("ingest_overhead_speedup")
            if isinstance(v, (int, float)) and not fab.get(
                "overhead_clamped"
            ):
                # a clamped decomposition (one arm below the 50 us/tick
                # floor) records evidence but must never flip a mapping —
                # the ratio's magnitude is the clamp's, not the rig's
                recommend("fleet_ingest_backend.tpu", ratio_entry(
                    "host", "fused",
                    "config10 fleet ingest_overhead_speedup",
                    float(v), "fleet_ingest_ab",
                ))
            out["evidence"].setdefault("fleet_ingest_ab", []).append({
                k: fab[k] for k in (
                    "ingest_overhead_speedup",
                    "fused_vs_host_tick_speedup",
                    "overhead_clamped",
                ) if k in fab
            })

        # config 11: the T-tick super-step drain A/B (super_tick_max
        # default recommendation)
        sab = rec.get("super_tick_ab")
        if isinstance(sab, dict):
            v = sab.get("drain_speedup")
            if isinstance(v, (int, float)) and not sab.get(
                "overhead_clamped"
            ):
                # a clamped decomposition (negative measured saving —
                # load weather on a drifting rig) records evidence but
                # must never move the default.  The recommended T is the
                # one the record actually measured (the artifact's
                # top-level super_tick), not a hardcoded constant.
                t_measured = rec.get("super_tick")
                recommend("super_tick_max.tpu", ratio_entry(
                    "1",
                    str(t_measured) if isinstance(t_measured, int) else "8",
                    "config11 super_tick drain_speedup",
                    float(v), "super_tick_ab",
                ))
            out["evidence"].setdefault("super_tick_ab", []).append({
                k: sab[k] for k in (
                    "drain_speedup", "per_dispatch_floor_ms",
                    "overhead_clamped",
                ) if k in sab
            })

        # config 12: the SLAM front-end A/B (map_backend mapping)
        mab = rec.get("mapping_ab")
        if isinstance(mab, dict):
            v = mab.get("match_speedup")
            if isinstance(v, (int, float)) and not mab.get(
                "overhead_clamped"
            ):
                # a clamped decomposition (negative measured saving —
                # load weather) records evidence but never flips
                recommend("map_backend.tpu", ratio_entry(
                    "host", "fused",
                    "config12 mapping match_speedup",
                    float(v), "mapping_ab",
                ))
            out["evidence"].setdefault("mapping_ab", []).append({
                k: mab[k] for k in (
                    "match_speedup", "per_dispatch_floor_ms",
                    "overhead_clamped",
                ) if k in mab
            })

        # config 14: the matcher-kernel A/B (match_backend mapping).
        # TWO clamps on top of the device=tpu rule: a clamped
        # decomposition (no measured saving) and an interpret-mode
        # record (the pallas arm ran the emulator, not Mosaic — a
        # malformed device field could otherwise smuggle one in)
        pmb = rec.get("pallas_match_ab")
        if isinstance(pmb, dict):
            v = pmb.get("match_speedup")
            if isinstance(v, (int, float)) and not pmb.get(
                "overhead_clamped"
            ) and not pmb.get("interpret_mode"):
                recommend("match_backend.tpu", ratio_entry(
                    "xla", "pallas",
                    "config14 pallas match_speedup",
                    float(v), "pallas_match_ab",
                ))
            out["evidence"].setdefault("pallas_match_ab", []).append({
                k: pmb[k] for k in (
                    "match_speedup", "overhead_clamped", "interpret_mode",
                ) if k in pmb
            })

        # config 15: the shard-failover pod A/B (shard_count default).
        # The key is a FLOOR, not a speedup bar: survivor-lane steady
        # throughput under a shard loss must stay >= 0.95x the paired
        # baseline before multi-shard pods are recommended as the
        # deployment default.  Under the strongest-evidence merge the
        # entry's strength must come from evidence AGAINST the flip
        # (the deep_window keep-entry discipline): a clean record
        # carries parity strength no matter how far ABOVE parity the
        # survivors ran — otherwise a 1.25x noise record outweighs a
        # genuine 0.85x degradation record (|log 1.25| > |log 0.85|)
        # and flips the default over committed floor-violation
        # evidence.  The measured ratio still lands in "measured" and
        # the evidence list.
        fov = rec.get("failover_ab")
        if isinstance(fov, dict):
            v = fov.get("survivor_steady_ratio")
            if isinstance(v, (int, float)) and not fov.get(
                "ratio_clamped"
            ):
                # a clamped ratio (one arm under the timer floor)
                # records evidence but never moves the default
                shards_m = fov.get("shards")
                proposed = (
                    str(shards_m) if isinstance(shards_m, int) else "4"
                )
                flip = v >= 0.95
                recommend("shard_count.tpu", {
                    "current": "1",
                    "recommended": proposed if flip else "1",
                    "flip": flip,
                    "key": "config15 survivor_steady_ratio",
                    "value": 1.0 if flip else float(v),
                    "measured": float(v),
                    "margin": 0.95,
                    "source": "failover_ab",
                })
            out["evidence"].setdefault("failover_ab", []).append({
                k: fov[k] for k in (
                    "survivor_steady_ratio", "shards", "streams",
                    "ratio_clamped",
                ) if k in fov
            })

        # config 16: the de-skew + sweep-reconstruction A/B
        # (deskew_enable default).  TWO gates on top of the device=tpu
        # rule: the clamp (one arm under the timer floor) and a
        # tick-ratio floor — the R× update multiplication is
        # architectural (asserted in the bench), so the flip question
        # is only whether the extra per-tick mapper work keeps the
        # fleet rate; a >= 2x multiplier with the tick ratio >= 0.90
        # is a win by construction.  Floor-style strength (the
        # failover_ab discipline): a clean record carries parity
        # strength so an above-parity noise record can never outweigh
        # committed evidence AGAINST the flip.
        dab = rec.get("deskew_ab")
        if isinstance(dab, dict):
            mult = dab.get("update_multiplier")
            ratio = dab.get("steady_tick_ratio")
            if (
                isinstance(mult, (int, float))
                and isinstance(ratio, (int, float))
                and not dab.get("ratio_clamped")
            ):
                flip = mult >= 2.0 and ratio >= 0.90
                recommend("deskew_enable.tpu", {
                    "current": "false",
                    "recommended": "true" if flip else "false",
                    "flip": flip,
                    "key": "config16 update_multiplier + steady_tick_ratio",
                    "value": 1.0 if flip else float(min(ratio, 1.0)),
                    "measured": {
                        "update_multiplier": float(mult),
                        "steady_tick_ratio": float(ratio),
                    },
                    "margin": 0.90,
                    "source": "deskew_ab",
                })
            out["evidence"].setdefault("deskew_ab", []).append({
                k: dab[k] for k in (
                    "update_multiplier", "steady_tick_ratio",
                    "ratio_clamped",
                ) if k in dab
            })

        # config 17: the SLAM back-end loop-closure A/B.  TWO mappings
        # ride one key: `loop_backend` flips host -> fused on the wall
        # ratio (clamped like every other overhead decomposition), and
        # `loop_enable` flips on the accuracy + cost pair — correction
        # within the 2-cell bar at < 10% steady-tick cost (the
        # deskew_ab decision shape)
        lab = rec.get("loop_close_ab")
        if isinstance(lab, dict):
            v = lab.get("backend_speedup")
            if isinstance(v, (int, float)) and not lab.get(
                "overhead_clamped"
            ):
                recommend("loop_backend.tpu", ratio_entry(
                    "host", "fused",
                    "config17 loop_close backend_speedup",
                    float(v), "loop_close_ab",
                ))
            err = lab.get("corrected_end_err_cells")
            ratio = lab.get("steady_tick_ratio")
            if isinstance(err, (int, float)) and isinstance(
                ratio, (int, float)
            ):
                # a clamped decomposition (back-end measured "free" —
                # below the timing floor) records evidence but must
                # never flip: the ratio's magnitude is the clamp's
                flip = (
                    err <= 2.0 and ratio >= 0.90
                    and not lab.get("overhead_clamped")
                )
                recommend("loop_enable.tpu", {
                    "current": "false",
                    "recommended": "true" if flip else "false",
                    "flip": flip,
                    "key": "config17 corrected_end_err_cells + "
                           "steady_tick_ratio",
                    "value": 1.0 if flip else float(min(ratio, 1.0)),
                    "measured": {
                        "corrected_end_err_cells": float(err),
                        "steady_tick_ratio": float(ratio),
                    },
                    "margin": 0.90,
                    "source": "loop_close_ab",
                })
            out["evidence"].setdefault("loop_close_ab", []).append({
                k: lab[k] for k in (
                    "backend_speedup", "steady_tick_ratio",
                    "corrected_end_err_cells", "baseline_end_err_cells",
                    "overhead_clamped",
                ) if k in lab
            })

        # config 18: the one-dispatch stack A/B (fused_mapping_backend
        # default).  The T+T -> 1 dispatch collapse is structural
        # (asserted in the bench), so the flip question is only whether
        # the in-program map update keeps the group rate: a steady
        # group ratio >= 0.95 is a win by construction (the collapse
        # removes a device round-trip per tick for free).  The clamp
        # (either arm under the timer floor) records evidence but must
        # never flip — the ratio's magnitude is the clamp's, and the
        # floor-asymmetric strength merge keeps an above-parity noise
        # record from displacing committed degradation evidence (the
        # failover_ab discipline).
        fmab = rec.get("fused_mapping_ab")
        if isinstance(fmab, dict):
            ratio = fmab.get("steady_group_ratio")
            if isinstance(ratio, (int, float)) and not fmab.get(
                "ratio_clamped"
            ):
                flip = ratio >= 0.95
                recommend("fused_mapping_backend.tpu", {
                    "current": "host",
                    "recommended": "fused" if flip else "host",
                    "flip": flip,
                    "key": "config18 steady_group_ratio",
                    "value": 1.0 if flip else float(min(ratio, 1.0)),
                    "measured": {
                        "steady_group_ratio": float(ratio),
                        "dispatch_collapse": fmab.get("dispatch_collapse"),
                    },
                    "margin": 0.95,
                    "source": "fused_mapping_ab",
                })
            out["evidence"].setdefault("fused_mapping_ab", []).append({
                k: fmab[k] for k in (
                    "steady_group_ratio", "dispatch_collapse",
                    "ratio_clamped",
                ) if k in fmab
            })

        # config 19: the traffic-shaped serving A/B (sched_rungs ladder
        # default).  The burst dispatch collapse, bounded backlog and
        # byte-equal-for-any-rung-sequence contract are structural
        # (asserted in the bench), so the flip question is only whether
        # the adaptive rung pick beats the static-T baseline on p99
        # drain latency on-chip: >= 1.05 (the standing noise bar) flips
        # the ladder on.  The clamp (either arm under the timer floor)
        # records evidence but must never flip — the ratio's magnitude
        # is the clamp's — and the floor-asymmetric strength merge
        # keeps an above-parity noise record from displacing committed
        # degradation evidence (the failover_ab discipline): a flipping
        # record carries parity strength, a violating one its measured
        # ratio.  CPU/interpret records carry no weight (device rule).
        esb = rec.get("elastic_serving_ab")
        if isinstance(esb, dict):
            v = esb.get("p99_speedup")
            if isinstance(v, (int, float)) and not esb.get(
                "ratio_clamped"
            ):
                rungs_m = esb.get("rungs")
                proposed = (
                    ",".join(str(r) for r in rungs_m)
                    if isinstance(rungs_m, list) and rungs_m
                    else "1,2,4,8"
                )
                flip = v >= MARGIN
                recommend("sched_rungs.tpu", {
                    "current": "static (rung 1 only)",
                    "recommended": (
                        proposed if flip else "static (rung 1 only)"
                    ),
                    "flip": flip,
                    "key": "config19 p99_speedup",
                    "value": 1.0 if flip else float(min(v, 1.0)),
                    "measured": float(v),
                    "margin": MARGIN,
                    "source": "elastic_serving_ab",
                })
            out["evidence"].setdefault("elastic_serving_ab", []).append({
                k: esb[k] for k in (
                    "p99_speedup", "rungs", "shards", "ratio_clamped",
                ) if k in esb
            })

        # config 20: the link-latency-hiding A/B (staging_double_buffer
        # + bucket_rungs default).  The staging/compute overlap, the
        # zero-recompile bucket switches and byte-equality are
        # structural (asserted in the bench), so the flip question is
        # only whether hiding the H2D stage beats the synchronous
        # baseline on p99 drain latency on-chip: >= 1.05 (the standing
        # noise bar) keeps the double buffer + ladder on.  The clamp
        # records evidence but must never flip, and the floor-
        # asymmetric strength merge keeps an above-parity noise record
        # from displacing committed degradation evidence (the
        # failover_ab discipline): a flipping record carries parity
        # strength, a violating one its measured ratio.  CPU/interpret
        # records carry no weight — a linkless rig has no H2D latency
        # to hide, so its ratio prices bookkeeping (device rule).
        asb = rec.get("async_serving_ab")
        if isinstance(asb, dict):
            v = asb.get("p99_speedup")
            if isinstance(v, (int, float)) and not asb.get(
                "ratio_clamped"
            ):
                buckets_m = asb.get("buckets")
                proposed = (
                    "double-buffered, bucket_rungs="
                    + ",".join(str(b) for b in buckets_m)
                    if isinstance(buckets_m, list) and buckets_m
                    else "double-buffered"
                )
                flip = v >= MARGIN
                recommend("staging_double_buffer.tpu", {
                    "current": "synchronous (PR14 static staging)",
                    "recommended": (
                        proposed if flip
                        else "synchronous (PR14 static staging)"
                    ),
                    "flip": flip,
                    "key": "config20 p99_speedup",
                    "value": 1.0 if flip else float(min(v, 1.0)),
                    "measured": float(v),
                    "margin": MARGIN,
                    "source": "async_serving_ab",
                })
            out["evidence"].setdefault("async_serving_ab", []).append({
                k: asb[k] for k in (
                    "p99_speedup", "buckets", "rungs", "overlap_hits",
                    "bucket_switches", "ratio_clamped",
                ) if k in asb
            })

        # config 21: the pod-of-pods A/B (steal_threshold_ticks +
        # autoscale_enable default).  The whole-queue steals, the
        # accounting identity, the full park/re-admit cycle and byte-
        # equality are structural (asserted in the bench), so the flip
        # question is only whether draining a deep shard's backlog on
        # a sibling's idle lanes beats the static pod on p99 drain
        # latency where shards really drain in parallel: >= 1.05 (the
        # standing noise bar) turns stealing + the autoscaler on.  The
        # clamp records evidence but must never flip, and the floor-
        # asymmetric strength merge keeps an above-parity noise record
        # from displacing committed degradation evidence (the
        # failover_ab discipline).  CPU/interpret records carry no
        # weight — a one-process rig serializes the shard drains, so
        # its per-tick max prices relocation, not the reclaimed idle
        # lanes (device rule).
        psb = rec.get("pod_scaleout_ab")
        if isinstance(psb, dict):
            v = psb.get("p99_speedup")
            if isinstance(v, (int, float)) and not psb.get(
                "ratio_clamped"
            ):
                flip = v >= MARGIN
                recommend("pod_scaleout.tpu", {
                    "current": "static pod (steal + autoscale off)",
                    "recommended": (
                        "steal + autoscale on" if flip
                        else "static pod (steal + autoscale off)"
                    ),
                    "flip": flip,
                    "key": "config21 p99_speedup",
                    "value": 1.0 if flip else float(min(v, 1.0)),
                    "measured": float(v),
                    "margin": MARGIN,
                    "source": "pod_scaleout_ab",
                })
            out["evidence"].setdefault("pod_scaleout_ab", []).append({
                k: psb[k] for k in (
                    "p99_speedup", "steals", "steal_ticks",
                    "scale_downs", "scale_ups", "hosts",
                    "ratio_clamped",
                ) if k in psb
            })

        # config 22: merged-world tile serving vs per-stream full-grid
        # pulls.  The read_speedup prices the link round-trips a
        # served snapshot read avoids: >= 1.05 keeps the world map +
        # tile plane on for map consumers.  The structure (zero added
        # dispatches, byte-exact merges, bounded residency) holds on
        # any rig, but only a real device link prices the pulls —
        # CPU/interpret records carry no weight (device rule), and
        # the timer-floor clamp records evidence without flipping.
        msb = rec.get("map_serving_ab")
        if isinstance(msb, dict):
            v = msb.get("read_speedup")
            if isinstance(v, (int, float)) and not msb.get(
                "ratio_clamped"
            ):
                flip = v >= MARGIN
                recommend("map_serving.tpu", {
                    "current": "per-stream full-grid pulls",
                    "recommended": (
                        "world map + tile snapshot serving" if flip
                        else "per-stream full-grid pulls"
                    ),
                    "flip": flip,
                    "key": "config22 read_speedup",
                    "value": 1.0 if flip else float(min(v, 1.0)),
                    "measured": float(v),
                    "margin": MARGIN,
                    "source": "map_serving_ab",
                })
            out["evidence"].setdefault("map_serving_ab", []).append({
                k: msb[k] for k in (
                    "read_speedup", "compression_ratio", "merges",
                    "evictions", "ratio_clamped",
                ) if k in msb
            })

        # config 23: scenario-matrix accuracy cells (corroboration
        # evidence, not a ratio — consumed by the post-pass below)
        sm = rec.get("scenario_matrix")
        if isinstance(sm, list):
            cells = [c for c in sm if isinstance(c, dict)]
            scenario_cells.extend(cells)
            out["evidence"].setdefault("scenario_matrix", []).append({
                "cells": len(cells),
                "clamped": sum(1 for c in cells if c.get("clamped")),
                "worst_end_pose_err_cells": rec.get(
                    "worst_end_pose_err_cells"
                ),
                "worst_map_f1": rec.get("worst_map_f1"),
            })

        # ablation: resample + voxel kernels
        derived = rec.get("derived")
        if isinstance(derived, dict):
            v = derived.get("matmul_vs_scatter_voxel_speedup")
            if isinstance(v, (int, float)):
                recommend("voxel_backend.tpu", ratio_entry(
                    "scatter", "matmul",
                    "matmul_vs_scatter_voxel_speedup", float(v), "ablation",
                ))
            v = derived.get("dense_vs_scatter_speedup")
            if isinstance(v, (int, float)):
                recommend("resample_backend.tpu", ratio_entry(
                    "scatter", "dense",
                    "dense_vs_scatter_speedup", float(v), "ablation",
                ))
            out["evidence"].setdefault("ablation_derived", []).append(derived)

    # scenario-corroboration post-pass: with config-23 cells in the
    # artifact set, an accuracy-coupled flip must show its subsystem
    # winning in >= 2 unclamped scenario cells or it is downgraded to
    # keep.  With NO scenario records the pass is inert — older
    # artifact sets keep their standing semantics (the matrix adds a
    # gate where it has evidence, it never invents one).
    if scenario_cells:
        for mapping, flag in _SCENARIO_CORROBORATION.items():
            entry = out["recommendations"].get(mapping)
            if entry is None:
                continue
            support = sum(
                1 for c in scenario_cells
                if c.get(flag) and not c.get("clamped")
            )
            entry["scenario_cells"] = support
            if entry.get("flip") and support < 2:
                entry["flip"] = False
                entry["recommended"] = entry["current"]
                entry["scenario_corroboration"] = (
                    f"insufficient: {support} < 2 unclamped cells"
                )
            else:
                entry["scenario_corroboration"] = (
                    f"{support} unclamped cells"
                )

    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("artifacts", nargs="+",
                    help="rig_recapture JSONL (or single-record JSON) files")
    args = ap.parse_args()

    result = analyze(list(_records(args.artifacts)))
    recs = result["recommendations"]
    if not recs:
        print("no TPU decision keys found in the given artifacts",
              file=sys.stderr)
    for name, r in recs.items():
        arrow = "FLIP ->" if r["flip"] else "keep"
        print(
            f"{name:40s} {r['current']:>10s} {arrow} {r['recommended']:<10s}"
            f" ({r['key']} = {r['value']:.3f}, bar {r['margin']})",
            file=sys.stderr,
        )
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
