"""Deep-window median A/B with the round-3 measurement discipline
(r3 VERDICT #6).

Deep-window temporal-median A/B — by default all THREE formulations
(pallas bitonic network / xla sort / incremental sliding median),
measured exactly like the headline: device-resident input, the step
loop inside ONE jit dispatch, RTT-adaptive in-jit iterations per round
so the single barrier fetch amortizes below ~5%, rounds INTERLEAVED
across the arms so link drift cancels.  The inc arm is the
long-context claim: its O(W) update vs the sorts' O(W log^2 W) should
WIDEN with window depth.

    python scripts/deep_window_ab.py [--windows 64 256 512] [--iters auto]

``--iters auto`` (default) sizes each backend's rounds off a measured
barrier RTT (bench._rtt_adaptive_iters) — a fixed count calibrated for
one day's link breaks on another's (the r4 recapture saw a ~200 ms RTT
eat 3000-iteration rounds whole).  Prints one human line per window to
stderr and ONE JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402 - safe pre-init (no device use at import)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--windows", type=int, nargs="+", default=[64, 256, 512])
    ap.add_argument("--backends", nargs="+",
                    default=["pallas", "xla", "inc_xla", "inc_pallas"],
                    choices=["pallas", "xla", "inc", "inc_xla", "inc_pallas"],
                    help="median arms to interleave (inc's O(W) update "
                    "vs the sorts' O(W log^2 W) should WIDEN with window "
                    "depth — the long-context scaling claim).  The inc "
                    "arms default PINNED per lowering: inc_xla is the "
                    "r3-continuity jnp formulation, inc_pallas the fused "
                    "VMEM kernel whose on-chip verdict decides the TPU "
                    "auto mapping; an unpinned 'inc' would change "
                    "meaning with the platform")
    ap.add_argument("--iters", type=bench.iters_arg, default="auto",
                    help="in-jit iterations per round, or 'auto' to size "
                    "off the measured barrier RTT (default)")
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--cpu", action="store_true",
                    help="CPU smoke mode (xla only makes sense there; "
                    "pallas runs in interpret mode — use tiny iters)")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        from rplidar_ros2_driver_tpu.utils.backend import guarded_backend_init

        ok, detail, _poisoned = guarded_backend_init(
            log=lambda m: print(m, file=sys.stderr, flush=True)
        )
        if not ok:
            print(json.dumps({"error": detail}))
            return 3

    import jax
    import numpy as np

    from bench import _ChainRunner
    from rplidar_ros2_driver_tpu.ops.filters import FilterConfig

    from rplidar_ros2_driver_tpu.utils.backend import (
        MeasurementWedgedError,
        exit_skipping_destructors,
        run_with_deadline,
    )

    auto = args.iters == "auto"
    base_iters = 3000 if auto else args.iters
    rtt_ms = None
    results = {}
    # a wedged mid-run fetch (link dies while a window measures) blocks
    # forever in native code: without a deadline the whole artifact —
    # including windows ALREADY measured — dies with the process (it
    # happened: W=256 completed, W=512 wedged, nothing was emitted).
    # One budget per window; a wedge poisons this process's backend, so
    # later windows are marked skipped rather than re-attempted.
    # per-window budget sized for the FOUR default arms (compile 20-40 s
    # each — slower at deep windows — plus RTT-adaptive sizing probes
    # plus 5 interleaved rounds <= 15 s per arm): the guard catches
    # wedges, and must not expire on a healthy-but-slow W=512 window
    window_deadline_s = float(
        os.environ.get("BENCH_WINDOW_DEADLINE_S", 1200)
    )
    wedged = None
    for window in args.windows:
        if wedged is not None:
            results[str(window)] = {
                "skipped": f"link wedged during W={wedged}"
            }
            continue
        try:
            def _measure_window() -> tuple[dict, dict, dict]:
                # runner construction sits under the deadline (warmup
                # does device_put + submit + a blocking D2H barrier —
                # the same round-trips that wedge) AND under the per-arm
                # guard (the warmup submit compiles the step, which is
                # exactly where a kernel lowering Mosaic rejects raises)
                nonlocal rtt_ms
                runners = {}
                arm_errors = {}
                for name in args.backends:
                    try:
                        runners[name] = _ChainRunner(
                            FilterConfig(
                                window=window, beams=bench.BEAMS,
                                grid=bench.GRID, cell_m=0.25,
                                median_backend=name,
                            ),
                            bench.POINTS,
                        )
                    except Exception as e:  # noqa: BLE001
                        arm_errors[name] = f"{type(e).__name__}: {e}"
                        print(f"W={window} arm {name} failed: {e}",
                              file=sys.stderr, flush=True)
                if not runners:
                    return {}, {}, arm_errors
                if rtt_ms is None and auto:
                    rtt_ms = next(
                        iter(runners.values())
                    ).measure_barrier_rtt_ms()
                iters_for = {}
                for n, r in list(runners.items()):
                    # an arm whose probe raises must not cost the other
                    # arms; with fixed --iters a tiny probe round still
                    # runs so compile failures surface HERE, not in the
                    # interleaved rounds loop (where they would discard
                    # the healthy arms' collected rounds)
                    try:
                        if auto:
                            iters_for[n] = bench._rtt_adaptive_iters(
                                r.measure_device_only, rtt_ms, base_iters
                            )
                        else:
                            r.measure_device_only(min(base_iters, 30))
                            iters_for[n] = base_iters
                    except Exception as e:  # noqa: BLE001
                        arm_errors[n] = f"{type(e).__name__}: {e}"
                        del runners[n]
                        print(f"W={window} arm {n} failed: {e}",
                              file=sys.stderr, flush=True)
                rounds: dict[str, list[float]] = {n: [] for n in runners}
                for _ in range(args.rounds):
                    for name, r in runners.items():  # interleaved
                        rounds[name].append(
                            r.measure_device_only(iters_for[name])
                        )
                return iters_for, rounds, arm_errors

            iters_for, rounds, arm_errors = run_with_deadline(
                _measure_window, window_deadline_s,
                what=f"W={window} measurement",
            )
            med = {n: float(np.median(v)) for n, v in rounds.items()}
            row = {
                f"{n}_scans_per_sec": round(med[n], 1)
                for n in args.backends if n in med
            }
            if arm_errors:
                row["arm_errors"] = arm_errors
            if "pallas" in med and "xla" in med:
                # the series-continuity key (pallas/xla, r3 onward)
                row["speedup"] = round(med["pallas"] / med["xla"], 3)
            sorts = [med[n] for n in ("pallas", "xla") if n in med]
            incs = [med[n] for n in ("inc", "inc_xla", "inc_pallas")
                    if n in med]
            if incs and sorts:
                # the crossover key: the best incremental formulation
                # against the best sort (per-arm rates ride alongside)
                row["inc_vs_best_sort_speedup"] = round(
                    max(incs) / max(sorts), 3
                )
            if "inc_pallas" in med and "inc_xla" in med:
                # the lowering A/B that decides what "inc" resolves to
                # on TPU (r4 VERDICT #2)
                row["inc_pallas_vs_inc_xla_speedup"] = round(
                    med["inc_pallas"] / med["inc_xla"], 3
                )
            row["rounds"] = {
                n: [round(x, 1) for x in v] for n, v in rounds.items()
            }
            row["round_iters"] = dict(iters_for)
            results[str(window)] = row
            print(
                "W=%d: %s" % (
                    window,
                    "  ".join(
                        f"{n} {med[n]:.0f}"
                        for n in args.backends if n in med
                    ),
                ),
                file=sys.stderr, flush=True,
            )
        except MeasurementWedgedError as e:
            # terminal for this process's backend: the blocked fetch
            # never returns, so later windows can only be skipped
            results[str(window)] = {"error": f"{type(e).__name__}: {e}"}
            wedged = window
            print(f"W={window}: WEDGED ({e})", file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 - a dead link mid-sequence
            # must not discard the windows already measured: rig time is
            # scarce, so completed results still reach the artifact
            results[str(window)] = {"error": f"{type(e).__name__}: {e}"}
            print(f"W={window}: FAILED ({e})", file=sys.stderr, flush=True)
    print(json.dumps({
        "deep_window_ab": results,
        "device": str(jax.devices()[0].platform),
        "iters": "auto" if auto else base_iters,
        **({"barrier_rtt_ms": round(rtt_ms, 3)} if rtt_ms is not None else {}),
        "rounds": args.rounds,
        "method": "device_resident_in_jit_interleaved",
    }), flush=True)
    if wedged is not None:
        exit_skipping_destructors(0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
