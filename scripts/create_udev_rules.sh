#!/usr/bin/env bash
# Thin wrapper over the packaged generator (tools/udev.py) — parity with the
# reference's scripts/create_udev_rules.sh: CP210x (10c4:ea60) -> /dev/rplidar,
# MODE 0666, group dialout, then udev reload + trigger.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m rplidar_ros2_driver_tpu.tools.udev --install "$@"
