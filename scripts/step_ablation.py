"""Per-stage ablation of the streaming filter step (r3 VERDICT #3).

Explains where the headline step's time goes by measuring the REAL
``counted_filter_step`` under config ablations (so the numbers cannot
drift from the production program): median on/off, voxel on/off, clip
on/off, and the grid-resample backend A/B (vmapped scatter-min vs the
dense one-hot tile — the fused replay path measured dense ~2x faster on
TPU; this script decides the STREAMING default per platform, feeding
``resolve_resample_backend``).

Measurement discipline is bench.py's ``measure_device_only`` pattern:
the step loops inside ONE jit dispatch (``_min_fold_loop``), outputs
fold into the carry so XLA cannot eliminate the work, and the section
ends with a dependent fetch — through a remote-attached device, a
per-dispatch loop or ``block_until_ready`` measures the link, not the
device (docs/BENCHMARKS.md).

    python scripts/step_ablation.py [--cpu] [--iters 3000] [--rounds 3]

Prints one human-readable table and ONE machine-readable JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402 - safe pre-init (no device use at import)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpu", action="store_true", help="force the CPU backend")
    ap.add_argument("--iters", type=bench.iters_arg, default="auto",
                    help="in-jit steps per round, or 'auto' (default) to "
                    "size rounds off the measured barrier RTT so the one "
                    "barrier fetch stays below ~5%% of a round — a fixed "
                    "count breaks when the rig's RTT shifts (r4: a ~200 ms "
                    "RTT added ~26 us/step to 3000-iteration rounds)")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--window", type=int, default=None,
                    help="override the headline 64-scan window")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        from rplidar_ros2_driver_tpu.utils.backend import guarded_backend_init

        ok, detail, _poisoned = guarded_backend_init(
            log=lambda m: print(m, file=sys.stderr, flush=True)
        )
        if not ok:
            print(json.dumps({"error": detail}))
            return 3

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rplidar_ros2_driver_tpu.ops.filters import (
        FilterConfig,
        FilterState,
        counted_filter_step,
        pack_host_scan_counted,
    )

    from rplidar_ros2_driver_tpu.filters.chain import resolve_median_backend

    device = jax.devices()[0]
    window = args.window or bench.WINDOW
    scan = bench._host_scans(1, bench.POINTS)[0]
    buf = pack_host_scan_counted(
        scan["angle_q14"], scan["dist_q2"], scan["quality"], None, bench.CAPACITY
    )

    def cfg(**over) -> FilterConfig:
        base = dict(
            window=window, beams=bench.BEAMS, grid=bench.GRID, cell_m=0.25,
            # resolve per the ACTUAL platform, not the --cpu flag: without
            # a TPU attached the probe still succeeds (CPU devices), and
            # pallas would run in interpret mode, poisoning the numbers
            median_backend=resolve_median_backend("auto", device.platform),
        )
        base.update(over)
        return FilterConfig(**base)

    def measure(c: FilterConfig, iters: int, rounds: int) -> float:
        """Best-of-rounds µs per streaming step for one config."""

        def step_ranges(st, p):
            st, out = counted_filter_step(st, p, c)
            return st, out.ranges

        run = bench._min_fold_loop(step_ranges, (c.beams,), iters)
        state = jax.device_put(FilterState.for_config(c), device)
        p = jax.device_put(buf, device)
        state, acc = run(state, p)  # compile outside the timed region
        bench._device_barrier(jnp.min(acc))
        best = None
        for _ in range(rounds):
            p = jax.device_put(buf, device)
            t0 = time.perf_counter()
            state, acc = run(state, p)
            bench._device_barrier(jnp.min(acc))
            dt = (time.perf_counter() - t0) / iters
            best = dt if best is None else min(best, dt)
        return best * 1e6

    cases = {
        "full_scatter": cfg(resample_backend="scatter"),
        "full_dense": cfg(resample_backend="dense"),
        "full_voxel_matmul": cfg(voxel_backend="matmul"),
        # median backends pinned explicitly: full_scatter's median is
        # whatever auto resolves to (pallas on TPU, inc on CPU), so the
        # inc-vs-sort comparison needs its own xla baseline to stay
        # reproducible after auto flips
        "full_median_xla": cfg(median_backend="xla"),
        "full_median_inc": cfg(median_backend="inc"),
        # the two pinned inc lowerings: the fused VMEM sorted_replace
        # kernel vs the jnp formulation (whose ~6 small ops each
        # round-trip HBM on TPU) — decides what "inc" auto-lowers to
        "full_median_inc_pallas": cfg(median_backend="inc_pallas"),
        "full_median_inc_xla": cfg(median_backend="inc_xla"),
        "no_median": cfg(enable_median=False),
        "no_voxel": cfg(enable_voxel=False),
        "no_clip": cfg(enable_clip=False),
        "resample_only": cfg(enable_median=False, enable_voxel=False,
                             enable_clip=False),
    }
    from rplidar_ros2_driver_tpu.utils.backend import (
        MeasurementWedgedError,
        exit_skipping_destructors,
        run_with_deadline,
    )

    # a wedged mid-run fetch would otherwise hang the process and lose
    # every case already measured (the deep-window A/B lost a completed
    # window exactly this way); one budget per case, partial artifact
    # on wedge
    case_deadline_s = float(os.environ.get("BENCH_CASE_DEADLINE_S", 600))

    auto = args.iters == "auto"
    iters = 3000 if auto else args.iters
    rtt_ms = None
    wedge_error = None
    us: dict[str, float] = {}
    case_errors: dict[str, str] = {}
    try:
        if auto:
            # probe the full step once, then size ALL cases' rounds off
            # the measured RTT (uniform iters keep the subtraction deltas
            # on an identical — and now negligible — per-step barrier
            # bias)
            def _size() -> tuple[float, int]:
                rtt = bench._barrier_rtt_ms(device)
                return rtt, bench._rtt_adaptive_iters(
                    lambda it: 1e6 / measure(cases["full_scatter"], it, 1),
                    rtt, iters,
                )

            rtt_ms, iters = run_with_deadline(
                _size, case_deadline_s, what="RTT-adaptive sizing probe"
            )
            print(f"auto: rtt {rtt_ms:.1f} ms -> {iters} iters/round",
                  file=sys.stderr, flush=True)
        for name, c in cases.items():
            try:
                us[name] = run_with_deadline(
                    lambda c=c: measure(c, iters, args.rounds),
                    case_deadline_s, what=f"ablation case {name}",
                )
            except Exception as e:  # noqa: BLE001 - dead link mid-case
                # a RAISING failure (RPC error etc.) must not discard
                # the cases already measured; a wedge is terminal for
                # the backend and aborts the sequence via the outer try
                if isinstance(e, MeasurementWedgedError):
                    raise
                case_errors[name] = f"{type(e).__name__}: {e}"
                print(f"{name:16s} FAILED ({e})",
                      file=sys.stderr, flush=True)
                continue
            print(f"{name:16s} {us[name]:8.2f} us/scan",
                  file=sys.stderr, flush=True)
    except MeasurementWedgedError as e:
        wedge_error = f"{type(e).__name__}: {e}"
        for name in cases:
            if name not in us and name not in case_errors:
                # same contract as deep_window_ab's skipped rows: a
                # reader must be able to tell "never attempted" from
                # "silently missing"
                case_errors[name] = "skipped: link wedged"
        print(f"WEDGED: {e}", file=sys.stderr, flush=True)

    def ratio(num: str, den: str):
        if num in us and den in us and us[den]:
            return round(us[num] / us[den], 3)
        return None

    derived = {
        # stage costs by subtraction from the full step (scatter
        # resample); entries whose inputs did not complete are omitted
        # rather than fabricated
        "median_us": (round(us["full_scatter"] - us["no_median"], 2)
                      if "full_scatter" in us and "no_median" in us else None),
        "voxel_us": (round(us["full_scatter"] - us["no_voxel"], 2)
                     if "full_scatter" in us and "no_voxel" in us else None),
        "clip_us": (round(us["full_scatter"] - us["no_clip"], 2)
                    if "full_scatter" in us and "no_clip" in us else None),
        "dense_vs_scatter_speedup": ratio("full_scatter", "full_dense"),
        "matmul_vs_scatter_voxel_speedup": ratio(
            "full_scatter", "full_voxel_matmul"
        ),
        # inc vs the explicit sort path (platform-independent baseline)
        "inc_vs_xla_median_speedup": ratio(
            "full_median_xla", "full_median_inc"
        ),
        # inc vs whatever auto currently resolves to (pallas on TPU —
        # the comparison that decides the TPU auto mapping)
        "inc_vs_auto_median_speedup": ratio(
            "full_scatter", "full_median_inc"
        ),
        # the inc lowering A/B: fused VMEM kernel vs jnp formulation
        # (decides what "inc" auto-lowers to per platform)
        "inc_pallas_vs_inc_xla_speedup": ratio(
            "full_median_inc_xla", "full_median_inc_pallas"
        ),
    }
    derived = {k: v for k, v in derived.items() if v is not None}
    print(json.dumps({
        "ablation_us": {k: round(v, 2) for k, v in us.items()},
        "derived": derived,
        **({"case_errors": case_errors} if case_errors else {}),
        **({"error": wedge_error} if wedge_error else {}),
        "device": str(device.platform),
        "window": window,
        "iters": iters,
        **({"barrier_rtt_ms": round(rtt_ms, 3)} if rtt_ms is not None else {}),
        "rounds": args.rounds,
        "method": "device_resident_in_jit",
    }), flush=True)
    if wedge_error is not None:
        exit_skipping_destructors(0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
