"""Live multi-stream pipelined fleet latency (r4 VERDICT #9).

Config 8 measures fleet REPLAY throughput; this measures the PRODUCTION
fleet tick: N SimulatedDevices stream DenseBoost wire frames, each
through its own RealLidarDriver (native channel -> batched decode ->
assembler), and one ``ShardedFilterService.submit_pipelined`` tick
stacks every stream's newest revolution onto the (stream, beam) mesh —
event-driven: a tick fires when every stream has a fresh revolution,
bounded by 1.5 revolution periods for laggard/idle streams.  The artifact records per-tick submit latency, the
per-publish latency distribution (anchored like config 6: a publish
event is triggered by the newest revolution's completed measurement and
carries the previous tick's output — one tick of declared staleness),
and the fleet keep-up ratio against the N x 10 scans/s device pace.

Reference frame: this is the fleet-scale analog of the double-buffered
acquisition/consumption overlap in the reference's ScanDataHolder
(/root/reference/src/sdk/src/sl_lidar_driver.cpp:237-371) — with the
whole fleet's filter work in ONE sharded dispatch per tick.

    python scripts/fleet_latency.py [--streams 4] [--seconds 10]
                                    [--rate-mult 1.0] [--cpu]
                                    [--fleet-ingest host|fused]

Prints ONE JSON line (progress to stderr).  All the decode work runs on
THIS host: on a 1-core box N streams at 1x pace contend for the core,
so the artifact records host_cpus alongside the keep-up ratio.

``--fleet-ingest fused`` is the A/B arm of the fleet-fused ingest
backend (driver/ingest.FleetFusedIngest): the drivers' decode sinks are
replaced with byte taps, and each fixed-period tick submits every
stream's RAW frame bytes in ONE pipelined fused dispatch — no host
decode at all.  Publish-tick pairing matches the host arm's ADVICE-r5
discipline by construction: the fused outputs carry their own back-dated
revolution end (ts0 + duration), so each publish latency is anchored to
ITS OWN revolution's measurement end, one tick of declared staleness.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402 - safe pre-init (no device use at import)


class _ByteTap:
    """Decoder-interface byte collector: installed as a driver's ingest
    sink (RealLidarDriver.set_ingest_sink) so the engine pump delivers
    raw measurement-frame runs here instead of decoding them.  The tick
    loop drains per-stream runs and feeds them to the fleet-fused
    engine — the driver's protocol layer (framing, mode negotiation)
    still runs; only decode+assembly move into the fused dispatch."""

    def __init__(self) -> None:
        import threading

        from rplidar_ros2_driver_tpu.protocol import timing as timingmod

        self.timing = timingmod.TimingDesc()
        self.recorder = None
        self._lock = threading.Lock()
        self._runs: list = []

    # -- the decoder interface the driver drives --
    def on_measurement_batch(self, ans_type: int, items: list) -> None:
        with self._lock:
            self._runs.append((int(ans_type), list(items)))

    def on_measurement(self, ans_type: int, payload: bytes) -> None:
        import time as _t

        self.on_measurement_batch(ans_type, [(payload, _t.monotonic())])

    def reset(self) -> None:
        with self._lock:
            self._runs.clear()

    def precompile(self, ans_type: int) -> None:
        pass  # the fleet engine precompiles; the tap has no kernels

    # -- the tick loop's drain --
    def drain(self):
        """One merged (ans_type, frames) run of everything pending, or
        None.  Mixed-type runs keep only the newest type's frames (a
        mode switch mid-tick; the older mode's tail is stale)."""
        with self._lock:
            runs, self._runs = self._runs, []
        if not runs:
            return None
        ans = runs[-1][0]
        frames: list = []
        for a, items in runs:
            if a == ans:
                frames.extend(items)
        return (ans, frames) if frames else None


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=4)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--rate-mult", type=float, default=1.0,
                    help="device pace multiplier (1.0 = 800 frames/s = "
                    "10 revolutions/s per stream)")
    ap.add_argument("--window", type=int, default=None,
                    help="override the headline 64-scan window")
    ap.add_argument("--cpu", action="store_true", help="force the CPU backend")
    ap.add_argument("--fleet-ingest", choices=("host", "fused"),
                    default="host",
                    help="ingest arm: host (drivers decode, one batched "
                    "sharded tick — the series default) or fused (byte "
                    "taps, one fleet-fused dispatch per tick — the A/B "
                    "arm of fleet_ingest_backend)")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        from rplidar_ros2_driver_tpu.utils.backend import guarded_backend_init

        ok, detail, _poisoned = guarded_backend_init(
            log=lambda m: print(m, file=sys.stderr, flush=True)
        )
        if not ok:
            print(json.dumps({"error": detail}))
            return 3

    import jax
    import numpy as np

    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import (
        SimConfig,
        SimulatedDevice,
    )
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService
    from rplidar_ros2_driver_tpu.utils.backend import (
        MeasurementWedgedError,
        exit_skipping_destructors,
        run_with_deadline,
    )

    if args.fleet_ingest == "fused":
        return _fused_main(args)

    n = args.streams
    window = args.window or bench.WINDOW
    # Tick policy: event-driven — tick as soon as EVERY stream has a
    # fresh revolution, or when 1.5 revolution periods elapse since the
    # last tick (laggard/idle-stream bound).  A fixed-phase tick at the
    # revolution period would add up to a full period of tick-boundary
    # wait to every publish latency, measuring the pacing loop instead
    # of the framework; with the all-live trigger the anchor measures
    # stream alignment skew + dispatch + collect.
    period_s = 0.1 / args.rate_mult
    tick_timeout_s = 1.5 * period_s
    params = DriverParams(
        filter_chain=("clip", "median", "voxel"),
        filter_window=window,
        voxel_grid_size=bench.GRID,
        voxel_cell_m=0.25,
        median_backend="auto",  # resolved per the mesh platform
        pipelined_publish=True,
    )

    sims = []
    drvs = []
    latest: list = [None] * n  # newest (scan, rev_end) per stream
    lk = threading.Lock()
    fresh = threading.Condition(lk)
    running = threading.Event()
    running.set()

    def pump(i: int, drv) -> None:
        while running.is_set():
            got = drv.grab_scan_host(0.5)
            if got is None:
                continue
            scan, ts0, duration = got
            with fresh:
                latest[i] = (scan, ts0 + duration)  # newest wins
                if all(s is not None for s in latest):
                    fresh.notify()

    threads = []
    result = {}
    try:
        svc = ShardedFilterService(
            params, streams=n, beams=bench.BEAMS, capacity=bench.CAPACITY
        )
        for _ in range(n):
            sim = SimulatedDevice(SimConfig(
                points_per_rev=bench.POINTS,
                frame_rate_hz=800.0 * args.rate_mult,
            )).start()
            sims.append(sim)
            drv = RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1",
                tcp_port=sim.port, motor_warmup_s=0.0,
            )
            assert drv.connect("sim", 0, False)
            drv.detect_and_init_strategy()
            assert drv.start_motor("DenseBoost", 600)
            drvs.append(drv)
        for i, drv in enumerate(drvs):
            t = threading.Thread(target=pump, args=(i, drv), daemon=True)
            t.start()
            threads.append(t)

        tick_s: list[float] = []
        pub_lat_s: list[float] = []
        published = 0
        ticks = 0
        live_in = 0
        measured_span_s = args.seconds
        # per-stream rev_end stashed at DISPATCH time: submit_pipelined
        # returns the PREVIOUS dispatch's outputs, so a publish must pair
        # with the revolution end recorded when ITS scan was dispatched,
        # not with whatever this tick's live mask happens to carry
        # (ADVICE r5 #1: mismatched-tick pairing skewed the latency
        # distribution for intermittently-laggard streams)
        pending_rev_end: list = [None] * n

        def _measured_run() -> None:
            nonlocal published, ticks, live_in, measured_span_s
            # warm the compile outside the measured span (all-idle tick)
            svc.submit_pipelined([None] * n)
            svc.flush_pipelined()
            t_start = time.monotonic()
            t_end = t_start + args.seconds
            while time.monotonic() < t_end:
                with fresh:
                    # all-live trigger with a laggard bound (see tick
                    # policy above); wake early when every stream is in
                    fresh.wait_for(
                        lambda: all(s is not None for s in latest),
                        timeout=tick_timeout_s,
                    )
                    scans = []
                    rev_end = []
                    for i in range(n):
                        if latest[i] is not None:
                            s, re = latest[i]
                            latest[i] = None
                            scans.append(s)
                            rev_end.append(re)
                        else:
                            scans.append(None)
                            rev_end.append(None)
                if all(s is None for s in scans):
                    continue  # timeout with nothing fresh: streams stalled
                t0 = time.monotonic()
                outs = svc.submit_pipelined(scans)
                t1 = time.monotonic()
                ticks += 1
                live_in += sum(s is not None for s in scans)
                tick_s.append(t1 - t0)
                for i, out in enumerate(outs):
                    if out is None:
                        continue
                    published += 1
                    if pending_rev_end[i] is not None:
                        # config-6 anchor: the publish is triggered by
                        # the newest revolution; the payload is declared
                        # one tick stale.  The latency anchor is the
                        # rev_end stashed at THIS output's dispatch tick.
                        pub_lat_s.append(t1 - pending_rev_end[i])
                        pending_rev_end[i] = None
                for i in range(n):
                    if scans[i] is not None:
                        pending_rev_end[i] = rev_end[i]
            # measured loop span, not nominal args.seconds: the loop
            # admits one final tick that starts before t_end and
            # completes after it (ADVICE r5 #3 — the nominal denominator
            # overstated throughput/keep-up on short smoke runs)
            measured_span_s = time.monotonic() - t_start
            svc.flush_pipelined()

        deadline_s = float(os.environ.get("BENCH_RUN_DEADLINE_S", 900))
        try:
            run_with_deadline(
                _measured_run, deadline_s, what="fleet latency measurement"
            )
        except MeasurementWedgedError as e:
            print(json.dumps({
                "metric": "fleet_live_pipelined_tick",
                "error": f"{type(e).__name__}: {e}",
                "ticks_completed": ticks,
            }), flush=True)
            exit_skipping_destructors(0)

        if ticks == 0 or published == 0:
            raise RuntimeError(
                f"fleet produced no output (ticks={ticks}, "
                f"published={published}) — sim streams broken?"
            )
        # quiesce the fleet BEFORE the link calibration: on a 1-core
        # host the still-running pumps would inflate the probe with
        # scheduler wait, overstating the very number readers subtract.
        # The finally block then runs over emptied lists (no-op).
        running.clear()
        for t in threads:
            t.join(timeout=2.0)
        threads.clear()
        for drv in drvs:
            try:
                drv.stop_motor()
                drv.disconnect()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        drvs.clear()
        for sim in sims:
            sim.stop()
        sims.clear()
        # link calibration, same convention as every other artifact: the
        # tick/publish latencies include device round-trips, and the
        # link's RTT is rig weather a reader must be able to subtract.
        # Deadline-bounded and optional: a link that wedges AFTER the
        # measured span must not cost the artifact (step_ablation's
        # convention).
        rtt_ms = None
        try:
            rtt_ms = run_with_deadline(
                lambda: bench._barrier_rtt_ms(jax.devices()[0]),
                60.0, what="RTT calibration probe",
            )
        except Exception:  # noqa: BLE001 - calibration is context, not data
            print("RTT calibration probe failed; artifact goes out "
                  "without it", file=sys.stderr, flush=True)
        # measured loop span, not nominal args.seconds (ADVICE r5 #3):
        # the loop admits one final tick that starts before t_end and
        # finishes after it, so the nominal denominator overstates
        # throughput and keep-up on short smoke runs
        elapsed = measured_span_s
        pace = 10.0 * args.rate_mult  # scans/s per stream at device pace
        result = {
            "metric": "fleet_live_pipelined_tick",
            "value": round(published / elapsed, 2),
            "unit": "scans/s",
            "vs_baseline": round(
                published / elapsed / (n * bench.BASELINE_SCANS_PER_SEC), 3
            ),
            "streams": n,
            "rate_mult": args.rate_mult,
            "nominal_seconds": args.seconds,
            "measured_span_s": round(elapsed, 3),
            "ticks": ticks,
            "live_inputs": live_in,
            "keep_up": round(published / (pace * n * elapsed), 3),
            # publishes vs revolutions actually submitted: structurally
            # <= 1 (each tick's outputs lag its inputs by one), and
            # load-robust where nominal-pace keep_up is weather — on a
            # throttled CI host the sims burst above nominal pace when
            # the scheduler starves then releases their pacing threads
            "keep_up_vs_input": round(published / max(live_in, 1), 3),
            "tick_p50_ms": round(float(np.percentile(tick_s, 50)) * 1e3, 3),
            "tick_p99_ms": round(float(np.percentile(tick_s, 99)) * 1e3, 3),
            "publish_p50_ms": round(
                float(np.percentile(pub_lat_s, 50)) * 1e3, 3
            ) if pub_lat_s else None,
            "publish_p99_ms": round(
                float(np.percentile(pub_lat_s, 99)) * 1e3, 3
            ) if pub_lat_s else None,
            "staleness_ticks": 1,
            "tick_policy": "all_live_or_1.5_period",
            **({"barrier_rtt_ms": round(rtt_ms, 3)}
               if rtt_ms is not None else {}),
            "points_per_scan": bench.POINTS,
            "window": window,
            "median_backend": svc.cfg.median_backend,
            "mesh": dict(svc.mesh.shape),
            "host_cpus": os.cpu_count() or 1,
            "device": str(jax.devices()[0].platform),
        }
    finally:
        running.clear()
        for t in threads:
            t.join(timeout=2.0)
        for drv in drvs:
            try:
                drv.stop_motor()
                drv.disconnect()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        for sim in sims:
            sim.stop()
    print(json.dumps(result), flush=True)
    return 0


def _fused_main(args) -> int:
    """The ``--fleet-ingest fused`` arm: N SimulatedDevices stream
    DenseBoost wire frames through their drivers' protocol pumps into
    per-stream byte taps; a fixed-period tick drains every tap and
    submits the raw bytes in ONE pipelined fleet-fused dispatch
    (driver/ingest.FleetFusedIngest.submit_pipelined).  Publish latency
    anchors on each revolution's own back-dated measurement end
    (ts0 + duration from the fused result) at collect time — the same
    per-revolution pairing as the host arm, one tick of declared
    staleness, with the tick-boundary wait honestly included (the fused
    arm has no all-live trigger: bytes, not revolutions, arrive)."""
    import jax
    import numpy as np

    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest
    from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
    from rplidar_ros2_driver_tpu.driver.sim_device import (
        SimConfig,
        SimulatedDevice,
    )
    from rplidar_ros2_driver_tpu.protocol.constants import Ans
    from rplidar_ros2_driver_tpu.utils.backend import (
        MeasurementWedgedError,
        exit_skipping_destructors,
        run_with_deadline,
    )

    n = args.streams
    window = args.window or bench.WINDOW
    period_s = 0.1 / args.rate_mult
    params = DriverParams(
        filter_backend="cpu" if args.cpu else "tpu",
        filter_chain=("clip", "median", "voxel"),
        filter_window=window,
        voxel_grid_size=bench.GRID,
        voxel_cell_m=0.25,
        fleet_ingest_backend="fused",
    )
    ans = int(Ans.MEASUREMENT_DENSE_CAPSULED)

    sims = []
    drvs = []
    taps = [_ByteTap() for _ in range(n)]
    result = {}
    try:
        # ~80 frames/stream/tick at 1x: one bucket holding a whole tick
        # keeps the dispatch count at exactly 1 per tick
        bucket = max(int(800.0 * args.rate_mult * period_s * 1.5), 8)
        fleet = FleetFusedIngest(
            params, n, beams=bench.BEAMS, capacity=bench.CAPACITY,
            buckets=(bucket,),
        )
        for i in range(n):
            sim = SimulatedDevice(SimConfig(
                points_per_rev=bench.POINTS,
                frame_rate_hz=800.0 * args.rate_mult,
            )).start()
            sims.append(sim)
            drv = RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1",
                tcp_port=sim.port, motor_warmup_s=0.0,
                ingest_sink=taps[i],
            )
            assert drv.connect("sim", 0, False)
            drv.detect_and_init_strategy()
            assert drv.start_motor("DenseBoost", 600)
            drvs.append(drv)
        # the drivers wrote the negotiated timing desc onto their taps;
        # the fused programs are compiled against it (homogeneous fleet —
        # one timing desc per config, like the single-stream engine)
        fleet.timing = taps[0].timing
        fleet.precompile([ans])

        tick_s: list[float] = []
        pub_lat_s: list[float] = []
        published = 0
        ticks = 0
        live_in = 0
        measured_span_s = args.seconds

        def _measured_run() -> None:
            nonlocal published, ticks, live_in, measured_span_s
            t_start = time.monotonic()
            t_end = t_start + args.seconds
            next_tick = t_start + period_s
            while time.monotonic() < t_end:
                now = time.monotonic()
                if now < next_tick:
                    time.sleep(min(next_tick - now, period_s))
                    continue
                next_tick += period_s
                items = [tap.drain() for tap in taps]
                if not any(items):
                    continue
                t0 = time.monotonic()
                outs = fleet.submit_pipelined(items)
                t1 = time.monotonic()
                ticks += 1
                live_in += sum(it is not None for it in items)
                tick_s.append(t1 - t0)
                for o in outs:
                    for _out, ts0, dur in o:
                        published += 1
                        # anchor: THIS revolution's back-dated
                        # measurement end (rx-derived, monotonic clock)
                        pub_lat_s.append(t1 - (ts0 + dur))
            measured_span_s = time.monotonic() - t_start
            for o in fleet.flush():
                published += len(o)

        deadline_s = float(os.environ.get("BENCH_RUN_DEADLINE_S", 900))
        try:
            run_with_deadline(
                _measured_run, deadline_s,
                what="fleet-fused latency measurement",
            )
        except MeasurementWedgedError as e:
            print(json.dumps({
                "metric": "fleet_live_pipelined_tick",
                "fleet_ingest": "fused",
                "error": f"{type(e).__name__}: {e}",
                "ticks_completed": ticks,
            }), flush=True)
            exit_skipping_destructors(0)

        if ticks == 0 or published == 0:
            raise RuntimeError(
                f"fused fleet produced no output (ticks={ticks}, "
                f"published={published}) — sim streams broken?"
            )
        for drv in drvs:
            try:
                drv.stop_motor()
                drv.disconnect()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        drvs.clear()
        for sim in sims:
            sim.stop()
        sims.clear()
        rtt_ms = None
        try:
            rtt_ms = run_with_deadline(
                lambda: bench._barrier_rtt_ms(jax.devices()[0]),
                60.0, what="RTT calibration probe",
            )
        except Exception:  # noqa: BLE001 - calibration is context, not data
            print("RTT calibration probe failed; artifact goes out "
                  "without it", file=sys.stderr, flush=True)
        elapsed = measured_span_s
        pace = 10.0 * args.rate_mult
        result = {
            "metric": "fleet_live_pipelined_tick",
            "fleet_ingest": "fused",
            "value": round(published / elapsed, 2),
            "unit": "scans/s",
            "vs_baseline": round(
                published / elapsed / (n * bench.BASELINE_SCANS_PER_SEC), 3
            ),
            "streams": n,
            "rate_mult": args.rate_mult,
            "nominal_seconds": args.seconds,
            "measured_span_s": round(elapsed, 3),
            "ticks": ticks,
            "live_inputs": live_in,
            "keep_up": round(published / (pace * n * elapsed), 3),
            "dispatches_per_tick": round(fleet.dispatch_count / ticks, 2),
            "h2d_per_tick": round(fleet.h2d_transfers / ticks, 2),
            "tick_p50_ms": round(float(np.percentile(tick_s, 50)) * 1e3, 3),
            "tick_p99_ms": round(float(np.percentile(tick_s, 99)) * 1e3, 3),
            "publish_p50_ms": round(
                float(np.percentile(pub_lat_s, 50)) * 1e3, 3
            ) if pub_lat_s else None,
            "publish_p99_ms": round(
                float(np.percentile(pub_lat_s, 99)) * 1e3, 3
            ) if pub_lat_s else None,
            "staleness_ticks": 1,
            "tick_policy": "fixed_period",
            **({"barrier_rtt_ms": round(rtt_ms, 3)}
               if rtt_ms is not None else {}),
            "points_per_scan": bench.POINTS,
            "window": window,
            "median_backend": fleet.cfg.median_backend,
            "host_cpus": os.cpu_count() or 1,
            "device": str(jax.devices()[0].platform),
        }
    finally:
        for drv in drvs:
            try:
                drv.stop_motor()
                drv.disconnect()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        for sim in sims:
            sim.stop()
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
